"""Per-request distributed tracing for the serving engine.

The telemetry layer (PR 2) aggregates: ``serving/*`` timers say *that*
latency moved, never *which request*, *which phase*, or *why* a deadline
was shed.  This module is the per-request attribution layer the
Ads-serving paper (PAPERS.md, arxiv 2501.10546) treats as the
precondition for operating continuous rollovers under live traffic:

- **Span contexts.** A ``Trace`` is one request's tree of ``Span``s
  (trace_id, span_id, parent, monotonic start/end, typed attrs), created
  at ``ServingEngine.submit()`` and threaded through every lifecycle
  phase — admission, tokenize, queue wait, coalesce, pack, h2d,
  dispatch, device execute, fetch, decode, deliver — plus child spans
  for oversize split/re-join, canary shadow scoring, and
  ``ExtractorPool`` calls.  Timestamps are HOST-side
  ``time.perf_counter`` reads only; the device-execute span ends at the
  existing async fetch boundary (the decode worker's blocking
  ``np.asarray``), so tracing adds **zero host syncs and zero compiles**
  (graftlint's host-sync / recompile-hazard rules still pass).
- **Head sampling + tail retention.** ``sample_rate`` (the
  ``TRACING_SAMPLE_RATE`` knob) decides at trace creation whether a
  trace is written to the span log; any trace that is shed, expired,
  degraded, split, closed mid-flight, errored, or slower than
  ``slow_ms`` (``TRACING_SLOW_MS``) is retained regardless — the traces
  an SLO postmortem actually needs are never sampled away.
- **Flight recorder.** A bounded ring holds the last ``flight_traces``
  completed traces (sampled or not) and dumps them to
  ``flight_<event>.jsonl`` on overload bursts, canary rollback, breaker
  open, and engine close — the serving analogue of the divergence
  guard's ``divergence_step<k>.json`` (PR 3).

Span names are cataloged in ``SPAN_CATALOG``; the graftlint rule
``span-catalog`` (analysis/rules/span_catalog.py) lints every emission
site against it, the same pattern as the metric and fault-point
catalogs.  Analyze a span log with ``scripts/latency_report.py``
(p50/p95/p99 per phase x bucket x tier, queue-wait vs device-time
decomposition, slowest span trees, Chrome-trace/Perfetto export).

Dependency-free (stdlib only) and thread-safe: spans are recorded from
submitter threads, the dispatcher, and the decode workers.
"""
from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from typing import Deque, Dict, List, Optional

from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter

#: every span name a ``begin``/``span``/``span_at``/``event``/``single``
#: site may use, with what the span covers.  Keep OBSERVABILITY.md's
#: "Per-request serving traces" table in sync — the ``span-catalog``
#: lint checks the doc mentions every name, and that every name here is
#: actually wired at a call site.
SPAN_CATALOG: Dict[str, str] = {
    'serving.request': 'Root span of one submit(): creation to delivery '
                       '(or the typed terminal reason).',
    'serving.admission': 'Admission control: bound check, drain estimate '
                         'vs deadline, degradation ladder, reservation.',
    'serving.tokenize': 'Caller-thread tokenize of the raw context lines '
                        'into a plane batch (reader.process_input_rows).',
    'serving.queue_wait': 'Enqueue to dispatcher pop (includes the '
                          'coalescing window the batch head opened).',
    'serving.coalesce': 'Batch-level: head-request enqueue to pop — the '
                        'micro-batcher gathering window (overlaps the '
                        'member requests\' queue_wait; excluded from '
                        'phase sums).',
    'serving.stall': 'Injected slow_dispatch fault stall (drills only).',
    'serving.pack': 'Merge + pad to bucket + packed-wire pack of the '
                    'coalesced micro-batch.',
    'serving.h2d': 'Sharded host-to-device placement of the packed '
                   'arrays (mesh.shard_batch).',
    'serving.dispatch': 'Async enqueue of the warm predict program '
                        '(plus the canary shadow dispatch when armed).',
    'serving.device_execute': 'Dispatch return to fetch completion at '
                              'the async fetch boundary: device execute '
                              '+ D2H + decode-worker handoff, with NO '
                              'added sync.',
    'serving.fetch': 'The blocking device fetch itself (decode worker '
                     'np.asarray), nested inside device_execute.',
    'serving.decode': 'Host-side top-k word lookup / attention parsing '
                      'of the fetched arrays.',
    'serving.deliver': 'Resolving one request\'s future with its rows.',
    'serving.shed': 'Terminal: shed at admission with EngineOverloaded '
                    '(attrs carry the reason).',
    'serving.expired': 'Terminal: SLO deadline passed while queued '
                       '(DeadlineExceeded, never dispatched).',
    'serving.degraded': 'Admitted at a downgraded tier by the overload '
                        'ladder (attrs: requested/effective tier).',
    'serving.closed': 'Terminal: engine closed with the request still '
                      'queued (EngineClosed).',
    'serving.chunk': 'One oversize-split chunk; its phases nest here '
                     'instead of under the root.',
    'serving.join': 'Oversize re-join: the last chunk merged the '
                    'ordered rows back into the caller future.',
    'serving.canary_shadow': 'One shadow-scored canary micro-batch '
                             '(attrs: step, rows, agreement tally).',
    'serving.redispatch': 'The request\'s batch died with its mesh '
                          'replica: re-admitted ONCE at the queue '
                          'front with the dead incarnation excluded '
                          '(attrs: replica, reason); a second '
                          'queue_wait span follows, so the trace '
                          'shows both attempts.',
    'serving.remote': 'Remote-worker envelope: one dispatched member\'s '
                      'worker-side execution (receipt to finish), '
                      'recorded in the worker process and grafted into '
                      'the parent trace by adopt_spans (attrs: replica, '
                      'pid).  A redispatched request shows one per '
                      'incarnation that did device work.',
    'serving.memo_hit': 'Terminal: the request was served from the '
                        'memoization tier at mesh admission — zero '
                        'device-seconds, no queue slot (attrs: tier, '
                        'rows, memo=exact|semantic); '
                        'latency_report.py --fleet attributes the '
                        'saved work off these.',
    'extractor.call': 'One ExtractorPool call (attrs: attempt count, '
                      'breaker state, outcome).',
    'autoscale.transition': 'One autoscaler scale transition, decision '
                            'to seated/retired replica (attrs: '
                            'direction=up|down, replicas, queue drain '
                            'estimate, burn flags; status=error on a '
                            'failed spawn/drain).',
}

#: span names that originate in a REMOTE worker process and reach the
#: parent's span log only through ``Trace.adopt_spans`` (the mesh wire
#: backhaul) — the ``span-catalog`` lint treats these as wired even
#: with no local literal emission site, and still requires catalog +
#: OBSERVABILITY.md coverage
REMOTE_ORIGIN_SPANS = frozenset(('serving.remote',))

#: span names whose presence marks a trace for tail retention even when
#: head sampling skipped it
TAIL_SPANS = frozenset((
    'serving.shed', 'serving.expired', 'serving.degraded',
    'serving.closed', 'serving.chunk', 'serving.stall',
    'serving.redispatch',
))

#: flight-recorder dump debounce: repeated same-event dumps inside this
#: window are skipped (a shed storm must not rewrite the file per shed)
DUMP_MIN_INTERVAL_S = 30.0
#: overload burst detector: this many sheds inside the window dump the
#: flight recorder once (debounced above)
SHED_BURST = 8
SHED_WINDOW_S = 1.0


class Span:
    """One timed phase. ``t0``/``t1`` are ``time.perf_counter`` seconds
    (host monotonic — comparable only within one process)."""

    __slots__ = ('span_id', 'parent_id', 'name', 't0', 't1', 'attrs')

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 t0: float, t1: Optional[float] = None,
                 attrs: Optional[dict] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs

    def record(self, trace_id: str) -> dict:
        t1 = self.t1 if self.t1 is not None else self.t0
        rec = {'trace': trace_id, 'span': self.span_id,
               'parent': self.parent_id, 'name': self.name,
               't0': self.t0, 't1': t1,
               'dur_ms': (t1 - self.t0) * 1e3}
        if self.attrs:
            rec['attrs'] = self.attrs
        return rec


class Trace:
    """One request's span tree.  ``finish`` is idempotent; spans added
    after it are dropped (a racing close cannot corrupt the log)."""

    # spans are appended from the submitter thread, the dispatcher, and
    # decode workers; finish() races close() (lock-discipline rule,
    # ANALYSIS.md):
    # graftlint: guard Trace._spans,_span_seq,_finished by _lock
    __slots__ = ('tracer', 'trace_id', 'sampled', 'root', '_spans',
                 '_span_seq', '_finished', '_lock')

    def __init__(self, tracer: 'Tracer', trace_id: str, sampled: bool,
                 root_name: str, t0: float, attrs: Optional[dict]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.sampled = sampled
        self._lock = threading.Lock()
        self._span_seq = 1
        self._finished = False
        self.root = Span(0, None, root_name, t0, attrs=attrs)
        self._spans: List[Span] = [self.root]

    def _add(self, name: str, t0: float, t1: Optional[float],
             parent: Optional[Span], attrs: Optional[dict]) -> Span:
        parent_id = parent.span_id if parent is not None else 0
        with self._lock:
            if self._finished:
                # orphan: never recorded (delivery raced a close/finish)
                return Span(-1, parent_id, name, t0, t1, attrs)
            span = Span(self._span_seq, parent_id, name, t0, t1, attrs)
            self._span_seq += 1
            self._spans.append(span)
        return span

    def span(self, name: str, parent: Optional[Span] = None,
             t0: Optional[float] = None,
             attrs: Optional[dict] = None) -> Span:
        """Open a span (end it with ``end``; ``finish`` closes leftovers
        at the trace end so shutdown never truncates one)."""
        return self._add(name, time.perf_counter() if t0 is None else t0,
                         None, parent, attrs)

    def span_at(self, name: str, t0: float, t1: float,
                parent: Optional[Span] = None,
                attrs: Optional[dict] = None) -> Span:
        """Record an already-measured (closed) span."""
        return self._add(name, t0, t1, parent, attrs)

    def event(self, name: str, parent: Optional[Span] = None,
              attrs: Optional[dict] = None) -> Span:
        """Zero-duration marker span (shed/expired/degraded reasons)."""
        now = time.perf_counter()
        return self._add(name, now, now, parent, attrs)

    def end(self, span: Span, t1: Optional[float] = None) -> None:
        t1 = time.perf_counter() if t1 is None else t1
        with self._lock:
            if self._finished:
                # finish() already closed leftovers and serialized the
                # trace (the aggregate-completing chunk ends its deliver
                # and chunk spans after the join finished the shared
                # trace); re-stamping would diverge from the written log
                return
            span.t1 = t1

    def adopt_spans(self, records: List[dict], offset_s: float = 0.0,
                    parent: Optional[Span] = None) -> int:
        """Graft REMOTE span records (a worker-side trace's serialized
        spans, shipped back over the mesh wire) into this live trace —
        the cross-process stitching half of the fleet observability
        plane (OBSERVABILITY.md "Fleet observability").

        Remote span ids are remapped onto this trace's id sequence (so
        two incarnations' subtrees can never collide), remote-internal
        parent links are preserved through the remap, a remote root
        (parent None) is re-parented under ``parent`` (the member's
        chunk span, or this trace's root), and every stamp is shifted
        by ``offset_s`` — the per-worker ``ClockOffset`` estimate that
        makes cross-host stamps order correctly.  Returns how many
        spans were adopted; 0 when the trace already finished (its log
        record is written — late arrivals cannot be stitched and the
        caller counts them dropped)."""
        if not records:
            return 0
        parent_id = parent.span_id if parent is not None else 0
        with self._lock:
            if self._finished:
                return 0
            idmap: Dict[int, int] = {}
            for rec in records:
                new_id = self._span_seq
                self._span_seq += 1
                idmap[rec['span']] = new_id
                remote_parent = rec.get('parent')
                self._spans.append(Span(
                    new_id,
                    idmap.get(remote_parent, parent_id)
                    if remote_parent is not None else parent_id,
                    rec['name'],
                    float(rec['t0']) + offset_s,
                    float(rec['t1']) + offset_s,
                    rec.get('attrs')))
            return len(records)

    def finish(self, status: str = 'ok',
               reason: Optional[str] = None) -> None:
        """Close the trace exactly once: stamp the root end, close any
        still-open spans at the same instant (no span is ever truncated
        by shutdown), and hand the trace to the tracer for the
        retention decision."""
        now = time.perf_counter()
        with self._lock:
            if self._finished:
                return
            self._finished = True
            # a pre-stamped root end (Tracer.single) is preserved
            for span in self._spans:
                if span.t1 is None:
                    span.t1 = now
            spans = list(self._spans)
        self.tracer._finish_trace(self, status, reason, spans)


class Tracer:
    """Span-log writer + flight recorder for one serving engine.

    ``out_dir=None`` runs memory-only: spans are recorded and the ring
    works (tests, engines with no artifact directory), but nothing is
    written and flight dumps are skipped.
    """

    # the ring, burst window, dump debounce, and id sequence are shared
    # by submitters, the dispatcher, and decode workers (lock-discipline
    # rule, ANALYSIS.md):
    # graftlint: guard Tracer._ring,_shed_times,_last_dump,_trace_seq,_closed by _lock
    def __init__(self, out_dir: Optional[str], sample_rate: float = 0.01,
                 slow_ms: float = 250.0, flight_traces: int = 256,
                 shed_burst: int = SHED_BURST,
                 shed_window_s: float = SHED_WINDOW_S,
                 dump_min_interval_s: float = DUMP_MIN_INTERVAL_S,
                 instance: Optional[str] = None,
                 log=None):
        self.out_dir = out_dir
        # instance namespaces the flight-recorder dumps
        # (flight_<event>_<instance>.jsonl): a worker-mode mesh replica
        # and its parent share one telemetry dir, and two processes
        # os.replace-ing the SAME flight_<event>.jsonl would clobber
        # each other's postmortems (latency_report.py globs both forms)
        self.instance = instance
        self.spans_path = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self.spans_path = os.path.join(out_dir, 'spans.jsonl')
        self.sample_rate = float(sample_rate)
        # <= 0 disables tail-retention-by-latency (0 would retain all)
        self.slow_s = slow_ms / 1e3 if slow_ms > 0 else float('inf')
        self.shed_burst = max(1, shed_burst)
        self.shed_window_s = shed_window_s
        self.dump_min_interval_s = dump_min_interval_s
        self.log = log if log is not None else (lambda msg: None)
        self.traces_total = Counter('tracing/traces_total')
        self.retained_total = Counter('tracing/retained_total')
        self.flight_dumps_total = Counter('tracing/flight_dumps_total')
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._ring: Deque = collections.deque(maxlen=max(1, flight_traces))
        self._shed_times: Deque[float] = collections.deque()
        self._last_dump: Dict[str, float] = {}
        self._trace_seq = 0
        self._closed = False
        self._id_prefix = '%08x' % random.getrandbits(32)
        self._rng = random.Random()

    # ------------------------------------------------------------ traces
    def begin(self, name: str, attrs: Optional[dict] = None) -> Trace:
        """Start one trace whose root span is ``name``; the head-based
        sampling decision is taken here."""
        with self._lock:
            seq = self._trace_seq
            self._trace_seq += 1
        sampled = self._rng.random() < self.sample_rate
        return Trace(self, '%s-%06d' % (self._id_prefix, seq), sampled,
                     name, time.perf_counter(), attrs)

    def single(self, name: str, attrs: Optional[dict] = None,
               t0: Optional[float] = None,
               t1: Optional[float] = None) -> None:
        """One-shot single-span trace for engine-level events that
        outlive their request traces (canary shadow scoring)."""
        trace = self.begin(name, attrs=attrs)
        if t0 is not None:
            trace.root.t0 = t0
        trace.sampled = True  # engine events are rare: always retained
        if t1 is not None:
            trace.root.t1 = t1
        trace.finish(status='ok')

    @staticmethod
    def _serialize(trace: Trace, status: str, wall: float,
                   spans: List[Span]) -> List[str]:
        lines = []
        for span in spans:
            rec = span.record(trace.trace_id)
            if span is trace.root:
                rec['status'] = status
                rec['sampled'] = trace.sampled
                rec['wall'] = wall
            lines.append(json.dumps(rec))
        return lines

    def _finish_trace(self, trace: Trace, status: str,
                      reason: Optional[str], spans: List[Span]) -> None:
        root = trace.root
        if reason is not None:
            root.attrs = dict(root.attrs or ())
            root.attrs['reason'] = reason
        dur_s = root.t1 - root.t0
        retained = (trace.sampled or status != 'ok'
                    or dur_s >= self.slow_s
                    or any(span.name in TAIL_SPANS for span in spans))
        self.traces_total.inc()
        if retained:
            self.retained_total.inc()
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('tracing/traces_total').inc()
            if retained:
                reg.counter('tracing/retained_total').inc()
        wall = time.time()
        # the ring keeps the SPANS, not serialized lines: the unsampled
        # fast path (the overwhelming majority at the default rate) pays
        # object appends only; json costs land on the rare retained
        # write or an actual flight dump
        with self._lock:
            self._ring.append((trace, status, wall, spans))
        if retained and self.spans_path is not None:
            payload = '\n'.join(self._serialize(trace, status, wall,
                                                spans)) + '\n'
            # one serialized append per trace: concurrent finishers
            # cannot tear each other's records
            with self._write_lock:
                with open(self.spans_path, 'a') as f:
                    f.write(payload)

    # --------------------------------------------------- flight recorder
    def note_shed(self) -> None:
        """Feed the overload burst detector with one shed; a burst dumps
        the flight recorder (debounced)."""
        now = time.monotonic()
        with self._lock:
            self._shed_times.append(now)
            while self._shed_times and \
                    now - self._shed_times[0] > self.shed_window_s:
                self._shed_times.popleft()
            burst = len(self._shed_times) >= self.shed_burst
        if burst:
            self.dump_flight('overload')

    def dump_flight(self, event: str,
                    force: bool = False) -> Optional[str]:
        """Dump the ring of recent traces to ``flight_<event>.jsonl``
        (atomic rewrite; debounced per event unless ``force``). Returns
        the path, or None when skipped/memory-only."""
        if self.out_dir is None:
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(event)
            if not force and last is not None and \
                    now - last < self.dump_min_interval_s:
                return None
            self._last_dump[event] = now
            ring = list(self._ring)
        suffix = '' if not self.instance else '_%s' % self.instance
        path = os.path.join(self.out_dir,
                            'flight_%s%s.jsonl' % (event, suffix))
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            f.write(json.dumps({'flight': event, 'time': time.time(),
                                'traces': len(ring)}) + '\n')
            for trace, status, wall, spans in ring:
                f.write('\n'.join(self._serialize(trace, status, wall,
                                                  spans)) + '\n')
        os.replace(tmp, path)  # postmortem readers never see a torn file
        self.flight_dumps_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter(
                'tracing/flight_dumps_total').inc()
        self.log('tracing: flight recorder dumped %d trace(s) -> %s '
                 '(event: %s)' % (len(ring), path, event))
        return path

    # --------------------------------------------------------- lifecycle
    def stats(self) -> Dict[str, object]:
        return {
            'traces_total': self.traces_total.snapshot(),
            'retained_total': self.retained_total.snapshot(),
            'flight_dumps_total': self.flight_dumps_total.snapshot(),
            'sample_rate': self.sample_rate,
            'spans_path': self.spans_path,
        }

    def close(self) -> None:
        """Final flight dump (``flight_close.jsonl``) — the engine calls
        this after the dispatcher and decode pool drained, so every
        in-flight trace has already been finished (delivered or typed-
        failed), never truncated.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.dump_flight('close', force=True)


class RemoteSpanSink:
    """Worker-side trace sink for cross-process stitching
    (OBSERVABILITY.md "Fleet observability").

    A worker-mode mesh replica runs the engine's span sites in its own
    process, where the parent's span log cannot see them.  The worker
    serve loop ``begin``s one trace per dispatched member UNDER the
    parent's shipped trace context (trace_id + parent span id), the
    engine records its phases into it exactly as it would locally, and
    when the trace finishes this sink serializes the spans into plain
    record dicts bundled with their (dispatch seq, member index) —
    nothing is written worker-side.  The serve loop ``collect``s the
    bundles onto the result frame; anything still in the outbox when a
    heartbeat fires rides the heartbeat instead (spans that finished
    after their result frame, or that a crash is about to orphan).
    The parent grafts them with ``Trace.adopt_spans``.

    The outbox is BOUNDED (``max_bundles``): with heartbeats disabled
    (``MESH_HEARTBEAT_SECS=0``) nothing sweeps orphans, and error-path
    bundles never get a result frame — stitching is best-effort
    observability, so past the cap the oldest bundles drop instead of
    growing the worker without bound.
    """

    # traces finish on the worker engine's decode threads while the
    # serve loop collects and the heartbeat thread drains
    # (lock-discipline rule, ANALYSIS.md); _cond wraps _lock:
    # graftlint: guard RemoteSpanSink._outbox,_open,dropped_bundles by _lock|_cond
    def __init__(self, replica: str, max_bundles: int = 512):
        self.replica = replica
        self.max_bundles = max(1, int(max_bundles))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._outbox: List[tuple] = []
        #: bundles evicted past the cap (never shipped)
        self.dropped_bundles = 0
        #: id(trace) -> (seq, member) for traces not yet finished
        self._open: Dict[int, tuple] = {}

    def begin(self, name: str, ctx: dict, seq: int,
              member: int) -> Trace:
        """One member's worker-side trace under the parent's context:
        the root span (``name``, normally ``serving.remote``) becomes a
        child of the parent's member span after adoption."""
        attrs = {'replica': self.replica, 'pid': os.getpid()}
        # the dispatch trace context carries the request's workload
        # scenario (WORKLOADS.md): stamped here so the worker-side
        # envelope is attributable per scenario after stitching
        if ctx.get('scenario') is not None:
            attrs['scenario'] = ctx['scenario']
        trace = Trace(self, str(ctx.get('trace_id', '?')),
                      bool(ctx.get('sampled')), name,
                      time.perf_counter(), attrs=attrs)
        with self._lock:
            self._open[id(trace)] = (seq, member)
        return trace

    def _finish_trace(self, trace: Trace, status: str,
                      reason: Optional[str], spans: List[Span]) -> None:
        root = trace.root
        if reason is not None:
            root.attrs = dict(root.attrs or ())
            root.attrs['reason'] = reason
        records = [span.record(trace.trace_id) for span in spans]
        with self._cond:
            seq, member = self._open.pop(id(trace), (None, None))
            self._outbox.append((time.perf_counter(),
                                 {'seq': seq, 'member': member,
                                  'trace': trace.trace_id,
                                  'status': status, 'spans': records}))
            overflow = len(self._outbox) - self.max_bundles
            if overflow > 0:
                del self._outbox[:overflow]
                self.dropped_bundles += overflow
            self._cond.notify_all()

    def wait_finished(self, traces: List[Optional[Trace]],
                      timeout: float) -> None:
        """Block (bounded) until every given trace has finished — they
        finish on the engine's decode threads moments after the member
        futures resolve, so the result frame almost always carries the
        full bundle set."""
        pending = {id(t) for t in traces if t is not None}
        deadline = time.perf_counter() + timeout
        with self._cond:
            while pending & set(self._open):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return
                self._cond.wait(min(remaining, 0.05))

    def collect(self, seq: int) -> List[dict]:
        """Pop the bundles belonging to dispatch ``seq`` — the result
        frame's piggyback.  Seq-keyed so a concurrently-firing
        heartbeat can never steal the result frame's bundles out from
        under the serve loop."""
        with self._lock:
            take = [bundle for _born, bundle in self._outbox
                    if bundle['seq'] == seq]
            self._outbox = [(born, bundle)
                            for born, bundle in self._outbox
                            if bundle['seq'] != seq]
        return take

    def drain(self, min_age_s: float = 0.0) -> List[dict]:
        """Pop bundles older than ``min_age_s`` — the heartbeat's
        orphan sweep.  The age gate leaves a just-finished bundle for
        its own result frame; a bundle still here after a beat period
        has evidently missed it (the serve loop is stalled or about to
        die with the result unsent) and ships now."""
        if min_age_s <= 0:
            with self._lock:
                taken, self._outbox = self._outbox, []
            return [bundle for _born, bundle in taken]
        now = time.perf_counter()
        with self._lock:
            take = [bundle for born, bundle in self._outbox
                    if now - born >= min_age_s]
            self._outbox = [(born, bundle)
                            for born, bundle in self._outbox
                            if now - born < min_age_s]
        return take
