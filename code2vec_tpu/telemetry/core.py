"""Telemetry core: counters, gauges, windowed timers, and the
process-global registry.

Design constraints (ISSUE 2 tentpole (a)):

- **Dependency-free** — stdlib only, importable from the data layer and
  the benchmarks without jax.
- **Thread-safe** — the input pipeline records from its prefetch thread
  while the training thread records step phases.  Each instrument guards
  its state with one lock; the registry guards get-or-create.
- **Near-zero cost when disabled** — call sites gate on ``enabled()``
  (one module-global bool read); nothing here allocates or reads clocks
  until a site decides to record.

Instruments are identified by catalog names (``telemetry/catalog.py``);
``scripts/check_metrics_schema.py`` lints every emission site against the
catalog so names cannot silently drift.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, Iterator, Optional, Tuple

__all__ = ['Counter', 'Gauge', 'Timer', 'MirrorTimer', 'Registry',
           'ScopedRegistry', 'registry', 'reset', 'enable', 'disable',
           'enabled']

# Module-global enablement. One bool read is the entire disabled-path
# cost at instrumented call sites.
_ENABLED = False


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


class Counter:
    """Monotonic counter. ``inc`` only; resets only via ``Registry.reset``."""

    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def snapshot(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar (ring occupancy, fill rate, rates)."""

    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> float:
        return self._value


class _TimerContext:
    """Re-usable ``with timer.time():`` context. A fresh tiny object per
    entry keeps the timer itself re-entrant across threads."""

    __slots__ = ('_timer', '_t0')

    def __init__(self, timer: 'Timer'):
        self._timer = timer
        self._t0 = 0.0

    def __enter__(self) -> '_TimerContext':
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.record(time.perf_counter() - self._t0)


class Timer:
    """Windowed duration statistics.

    Records durations in SECONDS; snapshots report milliseconds (metric
    names carry the ``_ms`` suffix).  Keeps cumulative ``count``/``total``
    plus a bounded window of recent samples; mean/percentiles/max are all
    computed over the window, so a long-running trainer's stats track the
    CURRENT regime, not the all-time mix (a warmup compile would
    otherwise poison the tail — and the max — forever).
    """

    # the window deque mutates under concurrent record()/snapshot()
    # (lock-discipline rule, ANALYSIS.md; the scalar _count/_total/_last
    # reads in the properties are deliberately lock-free — GIL-atomic):
    # graftlint: guard Timer._samples by _lock
    __slots__ = ('name', 'window', '_samples', '_count', '_total',
                 '_last', '_lock')

    def __init__(self, name: str = '', window: int = 512):
        self.name = name
        self.window = window
        self._samples: Deque[float] = collections.deque(maxlen=window)
        self._count = 0
        self._total = 0.0
        self._last = 0.0
        self._lock = threading.Lock()

    def time(self) -> _TimerContext:
        return _TimerContext(self)

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self._count += 1
            self._total += seconds
            self._last = seconds

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        """Cumulative seconds across ALL samples (not just the window)."""
        return self._total

    @property
    def last(self) -> float:
        """Most recent sample, in seconds."""
        return self._last

    def snapshot(self) -> Dict[str, float]:
        """{count, mean_ms, p50_ms, p95_ms, max_ms, last_ms, total_s} —
        mean/percentiles/max over the recent window, count/total
        cumulative."""
        with self._lock:
            samples = sorted(self._samples)
            count, total = self._count, self._total
            last = self._last
        if not samples:
            return {'count': 0, 'mean_ms': 0.0, 'p50_ms': 0.0, 'p95_ms': 0.0,
                    'max_ms': 0.0, 'last_ms': 0.0, 'total_s': 0.0}

        def pct(q: float) -> float:
            # nearest-rank on the sorted window
            idx = min(len(samples) - 1, max(0, int(q * len(samples))))
            return samples[idx] * 1e3

        return {'count': count,
                'mean_ms': sum(samples) / len(samples) * 1e3,
                'p50_ms': pct(0.50), 'p95_ms': pct(0.95),
                'max_ms': samples[-1] * 1e3, 'last_ms': last * 1e3,
                'total_s': total}


class MirrorTimer(Timer):
    """A Timer mirror fed by a REMOTE registry snapshot instead of
    local ``record`` calls — how a worker replica's timer stats join
    the parent's fleet export (serving/mesh.py telemetry backhaul,
    OBSERVABILITY.md "Fleet observability").

    The worker ships its timer's stat dict on each heartbeat;
    ``adopt`` stores it wholesale and ``snapshot`` replays it, so the
    JSONL/Prometheus exporters render the remote series exactly like a
    local one (it IS-A Timer for their isinstance dispatch).  Window
    semantics stay the worker's — the stats were computed over ITS
    sample window."""

    __slots__ = ('_stats',)

    def __init__(self, name: str = '', window: int = 512):
        super().__init__(name, window=window)
        self._stats: Optional[Dict[str, float]] = None

    def adopt(self, stats: Dict[str, float]) -> None:
        with self._lock:
            self._stats = dict(stats)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            stats = self._stats
        if stats is None:
            return super().snapshot()
        return dict(stats)


class Registry:
    """Thread-safe name -> instrument map with get-or-create accessors.

    One process-global instance (``registry()``): the input pipeline, the
    trainer, and the exporters all see the same instruments without
    threading a handle through every layer.
    """

    # get-or-create races between the input pipeline, trainer, and
    # exporter threads (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard Registry._instruments by _lock
    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    'metric %r is already registered as %s, not %s'
                    % (name, type(inst).__name__, cls.__name__))
            return inst

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def timer(self, name: str, window: int = 512) -> Timer:
        return self._get_or_create(name, Timer, window=window)

    def mirror_timer(self, name: str) -> MirrorTimer:
        """Get-or-create a remote-fed timer mirror (fleet merge only:
        the name should be replica-labeled, so it never collides with
        a locally recorded Timer)."""
        return self._get_or_create(name, MirrorTimer)

    def items(self) -> Iterator[Tuple[str, object]]:
        with self._lock:
            return iter(sorted(self._instruments.items()))

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._instruments.get(name)

    def snapshot(self) -> Dict[str, object]:
        """{name: scalar | timer-stat dict} for every instrument, in name
        order — the exporters' input."""
        return {name: inst.snapshot() for name, inst in self.items()}

    def reset(self) -> None:
        """Drop every instrument (test isolation; a fresh run re-creates
        what it touches)."""
        with self._lock:
            self._instruments.clear()


class ScopedRegistry:
    """Registry view that stamps every metric name with an instance
    label before it reaches the underlying registry: ``counter('serving/
    shed_total')`` on a ``ScopedRegistry(reg, 'replica', 'r1')`` creates
    ``serving/shed_total{replica=r1}``.

    This is how N coexisting serving-engine replicas mirror their
    instruments into the ONE process-global registry without
    double-counting each other's counters or overwriting each other's
    gauges (catalog.labeled / catalog.base_name define the name format;
    the schema lint and the Prometheus exporter resolve labeled names
    back to their catalog entry).  Stateless — safe to share across
    threads like the registry it wraps."""

    __slots__ = ('_registry', '_suffix')

    def __init__(self, registry: 'Registry', key: str, value: str):
        from code2vec_tpu.telemetry import catalog
        self._registry = registry
        self._suffix = catalog.label_suffix(key, value)

    def counter(self, name: str) -> Counter:
        return self._registry.counter(name + self._suffix)

    def gauge(self, name: str) -> Gauge:
        return self._registry.gauge(name + self._suffix)

    def timer(self, name: str, window: int = 512) -> Timer:
        return self._registry.timer(name + self._suffix, window=window)


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-global registry."""
    return _REGISTRY


def reset() -> None:
    """Clear the process-global registry (use between tests)."""
    _REGISTRY.reset()
