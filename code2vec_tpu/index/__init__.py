"""TPU-native embedding index: sharded k-NN search over code vectors.

The code2vec paper's headline downstream use is semantic retrieval —
"similar methods have nearby vectors" — but until this package the
framework could only WRITE vectors (serving/bulk.py, evaluate's
``--export_code_vectors``), never query them. The index closes the loop
extract → train → export → **search** (INDEX.md):

- ``store``   — on-disk memory-mapped vector store (+ labels), built
  from ``.vectors`` files, word2vec text exports, or streamed straight
  from ``bulk.iter_code_vector_batches`` without a text round-trip;
- ``exact``   — brute-force k-NN: one warm jitted matmul + the
  axis-general ``ops/topk.py::sharded_top_k`` merge, store rows sharded
  over the mesh data axis; plus a host-merge streamed tier for stores
  larger than device memory;
- ``ivf``     — approximate tier: on-device k-means coarse quantizer,
  inverted lists, ``nprobe``-bounded probing;
- ``quant``   — quantized tier: int8/PQ codes over the IVF lists
  (int8 = 1/2, PQ = ~1/8 the device bytes of f16) with a host-exact
  top-R re-rank, live insert segments, and compaction;
- ``service`` — build/load/query orchestration and the ServingEngine
  ``submit_neighbors`` composition (one warm round-trip from raw
  context lines to the K most similar corpus methods).
"""
from code2vec_tpu.index.store import VectorStore

__all__ = ['VectorStore']
