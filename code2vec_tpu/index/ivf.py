"""IVF approximate tier: k-means coarse quantizer + inverted lists.

For corpora that outgrow exact search, the classic two-level scheme:

1. **Build** — an on-device k-means over the store (jitted Lloyd
   iterations: assignment is one ``(N, D) @ (D, C)`` matmul + argmax,
   the update a ``segment_sum``), then the store rows are REORDERED into
   cluster-sorted order so each inverted list is a contiguous slice
   (CSR offsets) — probing is a segment-gather, not a scatter chase.
2. **Query** — score the C centroids (tiny), take the top ``nprobe``
   lists, gather their rows from the cluster-sorted matrix with one
   padded ``take`` (the candidate capacity rides
   ``data/packed.py::bucketed_capacity``, so the probe program
   specializes on a handful of capacities, not one per query batch),
   mask the padding to −inf, and top-k.

Probing ``nprobe`` of C lists scans ~``nprobe/C`` of the corpus;
recall depends on how clustered the vectors are (code vectors cluster by
construction — that is the paper's premise). The builder measures
recall@10 against the exact tier on a held-out query sample and reports
it (``index/recall_at10``); ``benchmarks/bench_index.py`` sweeps the
nprobe/recall/throughput curve.

Persistence: ``ivf.npz`` (centroids, cluster-sorted row ids, CSR
offsets) inside the store directory — the store shards stay the single
source of vector truth; loading re-sorts rows from the mmap.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from code2vec_tpu.data.packed import bucketed_capacity
from code2vec_tpu.index.store import VectorStore
from code2vec_tpu.telemetry import core as tele_core

IVF_NAME = 'ivf.npz'

DEFAULT_ITERS = 10
DEFAULT_NPROBE = 8
# probe-gather capacity floor (bucketed_capacity minimum): small enough
# that tiny test corpora stay cheap
MIN_PROBE_CAPACITY = 64


def default_clusters(count: int) -> int:
    """The classic sqrt(N) heuristic, floored at 1."""
    return max(1, int(np.sqrt(count)))


def kmeans(vectors: np.ndarray, n_clusters: int,
           iters: int = DEFAULT_ITERS, seed: int = 0
           ) -> Tuple[np.ndarray, np.ndarray]:
    """Jitted Lloyd iterations; returns (centroids (C, D) float32,
    assignment (N,) int32). Assignment maximizes the dot product —
    equivalent to min-L2 for the normalized rows of a cosine store.
    Empty clusters keep their previous centroid."""
    import jax
    import jax.numpy as jnp

    vectors = np.asarray(vectors, np.float32)
    n, dim = vectors.shape
    n_clusters = min(n_clusters, n)
    rng = np.random.default_rng(seed)
    init = vectors[rng.choice(n, size=n_clusters, replace=False)]

    @jax.jit
    def step(centroids, data):
        scores = data @ centroids.T                     # (N, C)
        assign = jnp.argmax(scores, axis=-1)
        sums = jax.ops.segment_sum(data, assign, num_segments=n_clusters)
        counts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), assign,
                                     num_segments=n_clusters)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        # empty cluster: keep the old centroid instead of collapsing to 0
        new = jnp.where((counts > 0)[:, None], means, centroids)
        return new, assign

    centroids = jnp.asarray(init)
    data = jnp.asarray(vectors)
    assign = None
    for _ in range(max(1, iters)):
        centroids, assign = step(centroids, data)
    return (np.asarray(centroids, np.float32),
            np.asarray(assign, np.int32))


class IVFIndex:
    """nprobe-bounded approximate k-NN over a built store.

    Build with ``IVFIndex.build(store, ...)`` (persists ``ivf.npz``) or
    reopen with ``IVFIndex(store)`` when the sidecar exists."""

    def __init__(self, store: VectorStore, centroids: np.ndarray = None,
                 list_ids: np.ndarray = None, offsets: np.ndarray = None,
                 nprobe: int = DEFAULT_NPROBE,
                 vectors: Optional[np.ndarray] = None):
        import jax

        self.store = store
        self.metric = store.metric
        self.labels = store.labels
        self.count = store.count
        self.dim = store.dim
        self.nprobe = nprobe
        if centroids is None:
            sidecar = os.path.join(store.path, IVF_NAME)
            if not os.path.isfile(sidecar):
                raise FileNotFoundError(
                    'no IVF sidecar at `%s` — build one with '
                    'IVFIndex.build(store) or --build-index '
                    '--index-kind ivf' % sidecar)
            data = np.load(sidecar)
            centroids = data['centroids']
            list_ids = data['list_ids']
            offsets = data['offsets']
        self.centroids = np.asarray(centroids, np.float32)
        self.n_clusters = self.centroids.shape[0]
        self.list_ids = np.asarray(list_ids, np.int64)
        self.offsets = np.asarray(offsets, np.int64)
        self.list_lengths = np.diff(self.offsets)
        # cluster-sorted rows, device-resident (replicated: the IVF
        # tier's win is scanning nprobe/C of the rows, and the padded
        # gather wants local rows; the sharded story is the exact
        # tier's). `vectors` lets build() hand over its already-loaded
        # array instead of a second all_rows() read; device residency
        # keeps the STORE dtype either way (f16 stores stay halved).
        rows = (np.asarray(vectors, store.dtype) if vectors is not None
                else store.all_rows())[self.list_ids]
        # HBM budget gate + ledger registration (telemetry/memory.py):
        # same attach-boundary contract as the exact tier
        from code2vec_tpu.telemetry import memory as memory_lib
        self.device_nbytes = (int(rows.nbytes)
                              + int(self.centroids.nbytes))
        memory_lib.ledger().check_budget(
            self.device_nbytes,
            'index attach (IVF tier: %d vectors x %d dims, %d clusters)'
            % (self.count, self.dim, self.n_clusters))
        try:
            self._sorted_rows = jax.device_put(rows)
            self._centroids_dev = jax.device_put(self.centroids)
        except Exception as exc:
            memory_lib.ledger().note_oom(exc, 'index.attach')
            raise
        memory_lib.ledger().register(
            'index', 'ivf:%x' % id(self), self.device_nbytes,
            owner=self, attrs={'tier': 'ivf', 'vectors': self.count,
                               'dim': self.dim,
                               'clusters': self.n_clusters})
        self._programs: Dict[Tuple[int, int, int], object] = {}

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, store: VectorStore, n_clusters: Optional[int] = None,
              iters: int = DEFAULT_ITERS, seed: int = 0,
              nprobe: int = DEFAULT_NPROBE, persist: bool = True,
              log=None) -> 'IVFIndex':
        t0 = time.perf_counter()
        n_clusters = (n_clusters if n_clusters
                      else default_clusters(store.count))
        vectors = np.asarray(store.all_rows(), np.float32)
        centroids, assign = kmeans(vectors, n_clusters, iters=iters,
                                   seed=seed)
        n_clusters = centroids.shape[0]
        # CSR inverted lists: stable sort keeps ascending row ids inside
        # each list (deterministic probe order)
        list_ids = np.argsort(assign, kind='stable').astype(np.int64)
        counts = np.bincount(assign, minlength=n_clusters)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        if persist:
            np.savez(os.path.join(store.path, IVF_NAME),
                     centroids=centroids, list_ids=list_ids,
                     offsets=offsets)
        build_s = time.perf_counter() - t0
        if tele_core.enabled():
            tele_core.registry().gauge('index/build_s').set(build_s)
        if log is not None:
            occupied = int((counts > 0).sum())
            log('index: IVF built — %d clusters (%d occupied, p50 list '
                '%d rows) over %d vectors in %.1fs'
                % (n_clusters, occupied,
                   int(np.median(counts[counts > 0])) if occupied else 0,
                   store.count, build_s))
        return cls(store, centroids=centroids, list_ids=list_ids,
                   offsets=offsets, nprobe=nprobe, vectors=vectors)

    # ------------------------------------------------------------ search
    def _program(self, q_bucket: int, capacity: int, k: int):
        # nprobe is deliberately NOT in the key: it shapes only the
        # host-side candidate fill, so an nprobe sweep (recall tuning,
        # bench_index.py) reuses one compiled program per shape
        key = (q_bucket, capacity, k)
        program = self._programs.get(key)
        if program is not None:
            return program
        import jax
        import jax.numpy as jnp

        from code2vec_tpu.ops.topk import padded_local_topk

        cosine = self.metric == 'cosine'

        def run(queries, sorted_rows, cand_ids):
            q = queries.astype(jnp.float32)
            if cosine:
                norms = jnp.linalg.norm(q, axis=-1, keepdims=True)
                q = q / jnp.where(norms > 0, norms, 1.0)
            # segment-gather of the probed lists: one padded take over
            # the cluster-sorted matrix
            rows = jnp.take(sorted_rows, jnp.maximum(cand_ids, 0),
                            axis=0)                     # (Q, cap, D)
            scores = jnp.einsum('qd,qcd->qc', q,
                                rows.astype(jnp.float32))
            scores = jnp.where(cand_ids >= 0, scores, -jnp.inf)
            return padded_local_topk(scores, k)

        program = jax.jit(run)
        self._programs[key] = program
        return program

    def _coarse(self, queries: np.ndarray, nprobe: int) -> np.ndarray:
        """Top-``nprobe`` cluster ids per query (host numpy — C is tiny
        next to N; the heavy gather+score runs jitted)."""
        q = queries
        if self.metric == 'cosine':
            norms = np.linalg.norm(q, axis=-1, keepdims=True)
            q = q / np.where(norms > 0, norms, 1.0)
        scores = q @ self.centroids.T
        return np.argsort(-scores, axis=-1, kind='stable')[:, :nprobe]

    def search(self, queries: np.ndarray, k: int,
               nprobe: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(Q, D) queries -> ((Q, k) scores, (Q, k) ORIGINAL row ids).
        Approximate: only the ``nprobe`` best inverted lists per query
        are scored. Queries with fewer than ``k`` candidates in their
        probed lists pad the tail with −inf/−1 sentinels."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        n = queries.shape[0]
        nprobe = min(self.n_clusters,
                     nprobe if nprobe is not None else self.nprobe)
        t0 = time.perf_counter()
        probe = self._coarse(queries, nprobe)            # (Q, nprobe)
        # candidate positions in the cluster-sorted matrix: contiguous
        # [offset, offset+len) runs per probed list, padded to a
        # bucketed capacity (warm shapes, like the packed wire)
        starts = self.offsets[probe]                     # (Q, nprobe)
        lengths = self.list_lengths[probe]
        totals = lengths.sum(axis=1)
        capacity = bucketed_capacity(int(totals.max(initial=1)),
                                     MIN_PROBE_CAPACITY)
        cand = np.full((n, capacity), -1, np.int64)
        for r in range(n):
            pos = 0
            for start, length in zip(starts[r], lengths[r]):
                cand[r, pos:pos + length] = np.arange(start,
                                                      start + length)
                pos += length
        from code2vec_tpu.index.exact import _pick_bucket
        from code2vec_tpu.index.exact import DEFAULT_QUERY_BUCKETS
        q_bucket = _pick_bucket(n, DEFAULT_QUERY_BUCKETS)
        if q_bucket != n:
            queries = np.concatenate(
                [queries, np.zeros((q_bucket - n, self.dim), np.float32)])
            cand = np.concatenate(
                [cand, np.full((q_bucket - n, capacity), -1, np.int64)])
        program = self._program(q_bucket, capacity, k)
        values, positions = program(queries, self._sorted_rows,
                                    cand.astype(np.int32))
        values = np.asarray(values)[:n]
        positions = np.asarray(positions)[:n]
        # positions index the (Q, capacity) candidate axis -> map back to
        # cluster-sorted positions, then through list_ids to row ids
        sorted_pos = np.take_along_axis(
            cand[:n], np.maximum(positions, 0).astype(np.int64), axis=-1)
        indices = np.where((positions >= 0) & (sorted_pos >= 0),
                           self.list_ids[np.maximum(sorted_pos, 0)], -1)
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('index/queries_total').inc(n)
            reg.timer('index/query_latency_ms').record(
                time.perf_counter() - t0)
            reg.gauge('index/probe_fanout').set(float(totals.mean()))
        return values, indices


def measure_recall(approx_index, exact_index, queries: np.ndarray,
                   k: int = 10, nprobe: Optional[int] = None) -> float:
    """recall@k of the approximate tier against the exact tier on a
    query sample: |approx ∩ exact| / k, averaged over queries."""
    _val_a, idx_a = approx_index.search(queries, k, nprobe=nprobe)
    _val_e, idx_e = exact_index.search(queries, k)
    hits = 0
    for row_a, row_e in zip(idx_a, idx_e):
        hits += len(set(int(i) for i in row_a if i >= 0)
                    & set(int(i) for i in row_e))
    recall = hits / float(idx_e.shape[0] * idx_e.shape[1])
    if tele_core.enabled():
        tele_core.registry().gauge('index/recall_at10').set(recall)
    return recall
