"""Brute-force exact k-NN over a device-resident, mesh-sharded store.

The search is one warm jitted program per (query bucket, k): a
``(Q, D) @ (D, N_shard)`` matmul per data shard — float32 accumulation
whatever the store dtype — a validity mask over the row-padding, and the
axis-general two-stage top-k merge from ``ops/topk.py`` (the same kernel
that merges the column-sharded softmax, here over the DATA axis: store
rows shard over ``data`` like eval batches, queries are replicated, and
only k candidates per shard cross the ICI).

Query batches ride a bucket ladder (``DEFAULT_QUERY_BUCKETS``, or the
``ExactIndex(query_buckets=...)`` parameter; bucket pick reuses the
serving engine's ``pick_bucket``), so steady-state search never
compiles — ``warmup()`` eagerly compiles the ladder and the
compile counter is asserted flat in tests/test_index_bench.py, the same
trick as tests/test_serving_bench.py.

Two tiers:

- ``ExactIndex`` — the whole store resident on device. The right tier
  whenever the store fits HBM (a java14m-scale corpus at 384 dims /
  float16 is ~10 GB — fits a v5e-8 data axis with room).
- ``search_streamed`` — stores larger than device memory: stream the
  mmap shards through a fixed-shape device chunk, per-shard
  ``padded_local_topk`` (a shard may hold FEWER than k rows — padded
  with −inf/−1 sentinels), and an exact host-side ``merge_topk_host``
  across shards with deterministic index tie-breaking.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.index.store import VectorStore, normalize_rows
from code2vec_tpu.telemetry import core as tele_core

DEFAULT_QUERY_BUCKETS = (1, 8, 64, 512)


def _pick_bucket(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder bucket covering ``n`` queries; oversize query
    batches fall back to the next multiple of the top bucket (compiles
    once per such size — callers chunk instead when they care)."""
    from code2vec_tpu.serving.engine import pick_bucket
    bucket = pick_bucket(n, ladder)
    if bucket is None:
        top = ladder[-1]
        bucket = -(-n // top) * top
    return bucket


class ExactIndex:
    """Device-resident exact-nearest-neighbor index over a store (or a
    raw ``(N, D)`` array for tests/benchmarks).

    ``mesh=None`` keeps everything on the default device (single-chip /
    CPU); a mesh shards store rows over its data axis."""

    def __init__(self, store, mesh=None,
                 metric: Optional[str] = None,
                 query_buckets: Sequence[int] = DEFAULT_QUERY_BUCKETS,
                 labels: Optional[np.ndarray] = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from code2vec_tpu.parallel.mesh import DATA_AXIS

        if isinstance(store, VectorStore):
            vectors = store.all_rows()
            self.metric = store.metric if metric is None else metric
            normalized = store.normalized
            self.labels = store.labels if labels is None else labels
        else:
            vectors = np.asarray(store)
            self.metric = 'cosine' if metric is None else metric
            normalized = False
            self.labels = labels
        if vectors.ndim != 2:
            raise ValueError('store must be (N, D), got %r'
                             % (vectors.shape,))
        if self.metric == 'cosine' and not normalized:
            vectors = normalize_rows(vectors).astype(vectors.dtype)
        self.count = int(vectors.shape[0])
        self.dim = int(vectors.shape[1])
        self.query_buckets = tuple(sorted(set(int(b)
                                              for b in query_buckets)))
        self.mesh = mesh
        self._data_axis = (mesh.shape[DATA_AXIS]
                           if mesh is not None else 1)
        # rows padded so every data shard holds an equal slice; padded
        # rows are masked to -inf and can never rank
        n_pad = -(-self.count // self._data_axis) * self._data_axis
        if n_pad != self.count:
            vectors = np.concatenate(
                [vectors, np.zeros((n_pad - self.count, self.dim),
                                   vectors.dtype)])
        self.padded_rows = n_pad
        neg_mask = np.zeros((n_pad,), np.float32)
        neg_mask[self.count:] = -np.inf
        # HBM budget gate (telemetry/memory.py): the attach boundary —
        # predict the device footprint from the host arrays and fail
        # typed BEFORE anything is placed, so a store that cannot fit
        # never half-allocates into a RESOURCE_EXHAUSTED
        from code2vec_tpu.telemetry import memory as memory_lib
        self.device_nbytes = int(vectors.nbytes) + int(neg_mask.nbytes)
        memory_lib.ledger().check_budget(
            self.device_nbytes,
            'index attach (exact tier: %d vectors x %d dims, %s)'
            % (self.count, self.dim, np.dtype(vectors.dtype).name))
        try:
            if mesh is not None and mesh.size > 1:
                self._matrix = jax.device_put(
                    vectors, NamedSharding(mesh, P(DATA_AXIS, None)))
                self._neg_mask = jax.device_put(
                    neg_mask, NamedSharding(mesh, P(DATA_AXIS)))
            else:
                self._matrix = jax.device_put(vectors)
                self._neg_mask = jax.device_put(neg_mask)
        except Exception as exc:
            memory_lib.ledger().note_oom(exc, 'index.attach')
            raise
        memory_lib.ledger().register(
            'index', 'exact:%x' % id(self), self.device_nbytes,
            owner=self, attrs={'tier': 'exact', 'vectors': self.count,
                               'dim': self.dim})
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.gauge('index/vectors_total').set(self.count)
            reg.gauge('index/shard_rows').set(n_pad // self._data_axis)
        self._programs: Dict[Tuple[int, int], object] = {}
        self._jnp = jnp

    # ---------------------------------------------------------- programs
    def _program(self, q_bucket: int, k: int):
        key = (q_bucket, k)
        program = self._programs.get(key)
        if program is not None:
            return program
        import jax
        import jax.numpy as jnp

        from code2vec_tpu.ops.topk import sharded_top_k
        from code2vec_tpu.parallel.mesh import DATA_AXIS

        mesh = self.mesh
        cosine = self.metric == 'cosine'
        sharded = mesh is not None and mesh.shape[DATA_AXIS] > 1

        def run(queries, matrix, neg_mask):
            q = queries.astype(jnp.float32)
            if cosine:
                norms = jnp.linalg.norm(q, axis=-1, keepdims=True)
                q = q / jnp.where(norms > 0, norms, 1.0)
            # float32 accumulation whatever the store dtype (float16
            # stores halve HBM; the MXU/VPU accumulates in f32 anyway)
            scores = jax.lax.dot_general(
                q.astype(matrix.dtype), matrix,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            scores = scores + neg_mask[None, :]
            if sharded:
                return sharded_top_k(scores, k, mesh,
                                     shard_axis=DATA_AXIS,
                                     batch_axis=None)
            return jax.lax.top_k(scores, k)

        program = jax.jit(run)
        self._programs[key] = program
        return program

    def warmup(self, k: int) -> 'ExactIndex':
        """Eagerly compile every query-bucket program for ``k``, so
        steady-state search never compiles."""
        import jax
        k = min(k, self.count)
        t0 = time.perf_counter()
        for bucket in self.query_buckets:
            queries = np.zeros((bucket, self.dim), np.float32)
            jax.block_until_ready(
                self._program(bucket, k)(queries, self._matrix,
                                         self._neg_mask))
        if tele_core.enabled():
            tele_core.registry().gauge('index/warmup_s').set(
                time.perf_counter() - t0)
        return self

    # ------------------------------------------------------------ search
    def search(self, queries: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(Q, D) queries -> ((Q, k) scores, (Q, k) row indices), exact,
        ranked by score then lowest index. ``k`` is capped at the store
        size. A single (D,) query is treated as Q=1."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        if queries.shape[1] != self.dim:
            raise ValueError('queries have dim %d, store has %d'
                             % (queries.shape[1], self.dim))
        k = min(k, self.count)
        n = queries.shape[0]
        bucket = _pick_bucket(n, self.query_buckets)
        if bucket != n:
            queries = np.concatenate(
                [queries, np.zeros((bucket - n, self.dim), np.float32)])
        t0 = time.perf_counter()
        values, indices = self._program(bucket, k)(
            queries, self._matrix, self._neg_mask)
        values = np.asarray(values)[:n]
        indices = np.asarray(indices)[:n]
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('index/queries_total').inc(n)
            reg.timer('index/query_latency_ms').record(
                time.perf_counter() - t0)
        return values, indices


# one jitted kernel shared by every search_streamed call: jit's cache is
# keyed on function identity + static args, so a per-call closure would
# retrace and recompile every invocation — exactly the warm-shape
# discipline the compile-counter guards enforce elsewhere
_streamed_program = None


def _streamed_shard_topk(queries, chunk, neg_mask, k: int):
    global _streamed_program
    if _streamed_program is None:
        import jax
        import jax.numpy as jnp

        from code2vec_tpu.ops.topk import padded_local_topk

        def shard_topk(q, rows, mask, kk):
            scores = jax.lax.dot_general(
                q.astype(rows.dtype), rows, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            return padded_local_topk(scores + mask[None, :], kk)

        _streamed_program = jax.jit(shard_topk, static_argnums=3)
    return _streamed_program(queries, chunk, neg_mask, k)


def search_streamed(store: VectorStore, queries: np.ndarray, k: int,
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact k-NN WITHOUT loading the store on device: stream the mmap
    shards through one fixed-shape device chunk each, take a per-shard
    ``padded_local_topk`` (−inf/−1 sentinels where a shard holds fewer
    than k rows), and merge the per-shard candidates exactly on the host
    (``merge_topk_host`` — deterministic index tie-breaking).

    Bit-for-rank identical to ``ExactIndex.search``
    (tests/test_index.py); the tier for stores larger than device
    memory. One compiled program serves every shard AND every call
    (module-level jitted kernel): all chunks pad to ``store.shard_rows``
    rows."""
    queries = np.atleast_2d(np.asarray(queries, np.float32))
    if store.metric == 'cosine':
        queries = normalize_rows(queries)
    k = min(k, store.count)
    n = queries.shape[0]
    q_bucket = _pick_bucket(n, DEFAULT_QUERY_BUCKETS)
    if q_bucket != n:
        queries = np.concatenate(
            [queries, np.zeros((q_bucket - n, store.dim), np.float32)])
    chunk_rows = min(store.shard_rows, max(k, max(store.shards)))

    cand_values = []
    cand_indices = []
    for offset, rows in store.iter_shards():
        rows = np.asarray(rows)
        pad = chunk_rows - rows.shape[0]
        neg_mask = np.zeros((chunk_rows,), np.float32)
        if pad:
            rows = np.concatenate(
                [rows, np.zeros((pad, store.dim), rows.dtype)])
            neg_mask[-pad:] = -np.inf
        # graftlint: disable=recompile-hazard -- every chunk pads to the store-constant chunk_rows: one compile per STORE, not per call (the shared module-level program above)
        values, indices = _streamed_shard_topk(queries, rows, neg_mask, k)
        values = np.asarray(values)
        indices = np.asarray(indices)
        # globalize real candidates; anything −inf (k-padding sentinels
        # AND selected chunk-padding rows) becomes the −1 sentinel so a
        # padding row's local index can never alias a later shard's real
        # global index
        indices = np.where(np.isfinite(values), indices + offset, -1)
        cand_values.append(values)
        cand_indices.append(indices)
    from code2vec_tpu.ops.topk import merge_topk_host
    values, indices = merge_topk_host(
        np.concatenate(cand_values, axis=-1),
        np.concatenate(cand_indices, axis=-1), k)
    return values[:n], indices[:n]
