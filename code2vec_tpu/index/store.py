"""On-disk vector store: memory-mapped shards + ``meta.json``.

Layout of ``<name>.vecindex/``::

    meta.json        {"count", "dim", "dtype", "metric", "normalized",
                      "shard_rows", "shards": [rows per shard]}
    shard_00000.bin  row-major (rows, dim) of meta's dtype
    shard_00001.bin  ...
    labels.txt       optional, one UTF-8 label per row (method names /
                     vocab words) — what a neighbor result displays
    ivf.npz          optional, written by index/ivf.py (centroids +
                     inverted lists); absent for exact-only stores

Shards are a DISK/streaming concept (bounded build memory, and the unit
of the exact tier's streamed host-merge search); the DEVICE layout is
separate — ``index/exact.py`` loads the whole store as one array sharded
over the mesh data axis, like eval batches.

Builders accept any iterable of ``(n_i, dim)`` float chunks, so the
index can be built straight from ``serving/bulk.iter_code_vector_batches``
without a round-trip through the ``.vectors`` text format, from an
existing ``.vectors`` file, or from a word2vec text export
(``--export_vocab_vectors``) whose words become the labels.

``dtype='float16'`` halves both disk and device-resident (HBM) footprint
(``Config.VECTORS_DTYPE``); scores are always accumulated in float32 on
device, and the recall impact is parity-tested (tests/test_index.py).

``metric='cosine'`` normalizes rows AT BUILD TIME (recorded as
``normalized`` in meta), so search never renormalizes the corpus side;
zero vectors stay zero and can never win a query.
"""
from __future__ import annotations

import json
import os
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.telemetry import core as tele_core

META_NAME = 'meta.json'
LABELS_NAME = 'labels.txt'
SHARD_PATTERN = 'shard_%05d.bin'
STORE_SUFFIX = '.vecindex'

METRICS = ('cosine', 'dot')
DTYPES = ('float32', 'float16')

# Rows per on-disk shard file: bounds build memory (one shard buffered
# at a time) and sizes the streamed search's per-shard device chunks.
DEFAULT_SHARD_ROWS = 1 << 18


def normalize_rows(vectors: np.ndarray) -> np.ndarray:
    """L2-normalize rows in float32; all-zero rows stay zero (a dropped
    example's vector must never be the nearest anything)."""
    vectors = np.asarray(vectors, np.float32)
    norms = np.linalg.norm(vectors, axis=-1, keepdims=True)
    return vectors / np.where(norms > 0, norms, 1.0)


class VectorStore:
    """Read view over a built store directory: memory-mapped shards,
    lazily opened, plus the meta fields as attributes."""

    def __init__(self, path: str):
        self.path = path
        meta_path = os.path.join(path, META_NAME)
        if not os.path.isfile(meta_path):
            raise FileNotFoundError(
                'no vector store at `%s` (missing %s)' % (path, META_NAME))
        with open(meta_path, 'r') as f:
            meta = json.load(f)
        self.count = int(meta['count'])
        self.dim = int(meta['dim'])
        self.dtype = np.dtype(meta['dtype'])
        self.metric = str(meta['metric'])
        self.normalized = bool(meta['normalized'])
        self.shard_rows = int(meta['shard_rows'])
        self.shards: List[int] = [int(n) for n in meta['shards']]
        if sum(self.shards) != self.count:
            raise ValueError(
                'corrupt store `%s`: shard rows %r do not sum to count %d'
                % (path, self.shards, self.count))
        self._labels: Optional[np.ndarray] = None
        self._mmaps: List[Optional[np.memmap]] = [None] * len(self.shards)

    # ------------------------------------------------------------ reading
    def shard(self, i: int) -> np.memmap:
        """Memory-mapped (rows_i, dim) view of shard ``i``."""
        if self._mmaps[i] is None:
            self._mmaps[i] = np.memmap(
                os.path.join(self.path, SHARD_PATTERN % i), mode='r',
                dtype=self.dtype, shape=(self.shards[i], self.dim))
        return self._mmaps[i]

    def iter_shards(self) -> Iterable[Tuple[int, np.memmap]]:
        """(global row offset, mmap rows) per shard, in row order."""
        offset = 0
        for i, rows in enumerate(self.shards):
            yield offset, self.shard(i)
            offset += rows

    def all_rows(self) -> np.ndarray:
        """The whole store as one (count, dim) array (device loading;
        copies out of the mmaps)."""
        if len(self.shards) == 1:
            return np.asarray(self.shard(0))
        return np.concatenate([np.asarray(s)
                               for _off, s in self.iter_shards()])

    @property
    def labels(self) -> Optional[np.ndarray]:
        """(count,) object array of per-row labels, or None."""
        if self._labels is None:
            labels_path = os.path.join(self.path, LABELS_NAME)
            if not os.path.isfile(labels_path):
                return None
            with open(labels_path, 'r', encoding='utf-8') as f:
                self._labels = np.array(
                    [line.rstrip('\n') for line in f], dtype=object)
            if self._labels.shape[0] != self.count:
                raise ValueError(
                    'corrupt store `%s`: %d labels for %d vectors'
                    % (self.path, self._labels.shape[0], self.count))
        return self._labels

    def label_of(self, row: int) -> Optional[str]:
        labels = self.labels
        return None if labels is None else str(labels[row])

    def take(self, rows: np.ndarray) -> np.ndarray:
        """Gather arbitrary global row ids out of the mmapped shards:
        ``(n,)`` int ids -> ``(n, dim)`` in the store dtype. The exact
        re-rank path of the quantized tier (index/quant.py) — candidate
        sets are tiny (top-R per query), so a per-shard fancy-index over
        the mmaps beats materializing ``all_rows()``."""
        rows = np.asarray(rows, np.int64).ravel()
        out = np.empty((rows.shape[0], self.dim), self.dtype)
        if rows.shape[0] == 0:
            return out
        if rows.min() < 0 or rows.max() >= self.count:
            raise IndexError(
                'row ids out of range [0, %d) for store `%s`'
                % (self.count, self.path))
        bounds = np.concatenate([[0], np.cumsum(self.shards)])
        shard_idx = np.searchsorted(bounds, rows, side='right') - 1
        for s in np.unique(shard_idx):
            mask = shard_idx == s
            out[mask] = self.shard(int(s))[rows[mask] - bounds[s]]
        return out

    # ---------------------------------------------------------- appending
    def append_rows(self, vectors: np.ndarray,
                    labels: Optional[Sequence[str]] = None,
                    canonical: bool = False) -> Tuple[int, int]:
        """Append rows as NEW shard files + an atomic meta update — the
        quantized tier's compaction path: segment truth folds into the
        store without rewriting existing shards. Returns the
        ``(start, end)`` global row id range of the appended rows.

        Normalization parity with build(): rows are L2-normalized here
        iff the store records ``normalized`` (cosine builds). With
        ``canonical`` the rows are written verbatim — the compaction
        path, whose segment vectors were already normalized and cast at
        insert time; re-normalizing would shift last-ulp bytes and break
        the pre/post-compaction bit-for-rank contract. A labeled store
        keeps its labels file row-aligned — appends without labels write
        blank lines; an unlabeled store refuses labels (labeling could
        not be backfilled for the existing rows)."""
        vectors = np.asarray(vectors)
        if vectors.ndim != 2 or (vectors.shape[0] > 0
                                 and vectors.shape[1] != self.dim):
            raise ValueError('appended vectors must be (n, %d), got %r'
                             % (self.dim, vectors.shape))
        n = int(vectors.shape[0])
        if n == 0:
            return (self.count, self.count)
        if self.normalized and not canonical:
            vectors = normalize_rows(vectors)
        vectors = np.ascontiguousarray(vectors, dtype=self.dtype)
        has_labels = self.labels is not None
        if labels is not None and not has_labels:
            raise ValueError(
                'store `%s` has no labels file — appended labels would '
                'mis-align with the existing unlabeled rows' % self.path)
        row_labels: List[str] = []
        if has_labels:
            row_labels = ([str(item) for item in labels]
                          if labels is not None else [''] * n)
            if len(row_labels) != n:
                raise ValueError(
                    '%d labels for %d appended vectors — the label '
                    'stream must align row-for-row' % (len(row_labels), n))
        start = self.count
        new_counts: List[int] = []
        written = 0
        while written < n:
            rows_here = min(self.shard_rows, n - written)
            shard_path = os.path.join(
                self.path, SHARD_PATTERN % (len(self.shards)
                                            + len(new_counts)))
            with open(shard_path, 'wb') as f:
                f.write(vectors[written:written + rows_here].tobytes())
            new_counts.append(rows_here)
            written += rows_here
        if has_labels:
            with open(os.path.join(self.path, LABELS_NAME), 'a',
                      encoding='utf-8') as f:
                for item in row_labels:
                    f.write(str(item).replace('\n', ' ') + '\n')
        meta = {'count': self.count + n, 'dim': self.dim,
                'dtype': self.dtype.name, 'metric': self.metric,
                'normalized': self.normalized,
                'shard_rows': self.shard_rows,
                'shards': self.shards + new_counts}
        # same atomic-ish discipline as build(): shard bytes land first,
        # meta last — a crash leaves orphan .bin files, never a store
        # whose meta points past the data
        meta_tmp = os.path.join(self.path, META_NAME + '.tmp')
        with open(meta_tmp, 'w') as f:
            json.dump(meta, f)
        os.replace(meta_tmp, os.path.join(self.path, META_NAME))
        self.count += n
        self.shards.extend(new_counts)
        self._mmaps.extend([None] * len(new_counts))
        self._labels = None
        if tele_core.enabled():
            tele_core.registry().gauge('index/vectors_total').set(
                self.count)
        return (start, self.count)


# ---------------------------------------------------------------- builders
def build(out_dir: str, chunks: Iterable[np.ndarray],
          dtype: str = 'float32', metric: str = 'cosine',
          labels: Optional[Iterable[str]] = None,
          shard_rows: int = DEFAULT_SHARD_ROWS,
          log=None) -> VectorStore:
    """Stream ``(n_i, dim)`` float chunks into a store directory.

    ``labels`` (optional) must yield exactly one string per row, aligned
    with the chunk stream — the builder depends on the bulk export's
    row i ↔ example i order guarantee (serving/bulk.py). It is consumed
    only AFTER the chunk iterable is exhausted, so a caller may pass a
    list its chunk generator is still appending to (late binding — how
    service.build_index streams a corpus without materializing it)."""
    if metric not in METRICS:
        raise ValueError('metric must be one of %s, got %r'
                         % (METRICS, metric))
    if np.dtype(dtype).name not in DTYPES:
        raise ValueError('dtype must be one of %s, got %r'
                         % (DTYPES, dtype))
    if shard_rows < 1:
        raise ValueError('shard_rows must be >= 1, got %d' % shard_rows)
    t0 = time.perf_counter()
    os.makedirs(out_dir, exist_ok=True)
    out_dtype = np.dtype(dtype)
    normalize = metric == 'cosine'
    dim = None
    count = 0
    shard_counts: List[int] = []
    shard_file = None

    def open_shard():
        return open(os.path.join(out_dir,
                                 SHARD_PATTERN % len(shard_counts)), 'wb')

    try:
        for chunk in chunks:
            chunk = np.asarray(chunk)
            if chunk.ndim != 2:
                raise ValueError('chunks must be (n, dim), got shape %r'
                                 % (chunk.shape,))
            if chunk.shape[0] == 0:
                continue
            if dim is None:
                dim = int(chunk.shape[1])
            elif chunk.shape[1] != dim:
                raise ValueError('chunk dim %d != first chunk dim %d'
                                 % (chunk.shape[1], dim))
            if normalize:
                chunk = normalize_rows(chunk)
            chunk = np.ascontiguousarray(chunk, dtype=out_dtype)
            written = 0
            while written < chunk.shape[0]:
                if shard_file is None:
                    shard_file = open_shard()
                    shard_counts.append(0)
                room = shard_rows - shard_counts[-1]
                take = min(room, chunk.shape[0] - written)
                shard_file.write(chunk[written:written + take].tobytes())
                shard_counts[-1] += take
                written += take
                count += take
                if shard_counts[-1] == shard_rows:
                    shard_file.close()
                    shard_file = None
    finally:
        if shard_file is not None:
            shard_file.close()
    if count == 0:
        raise ValueError('no vectors to index (empty chunk stream)')

    n_labels = 0
    if labels is not None:
        with open(os.path.join(out_dir, LABELS_NAME), 'w',
                  encoding='utf-8') as f:
            for label in labels:
                f.write(str(label).replace('\n', ' ') + '\n')
                n_labels += 1
        if n_labels != count:
            raise ValueError(
                '%d labels for %d vectors — the label stream must align '
                'row-for-row with the vector stream' % (n_labels, count))

    meta = {'count': count, 'dim': dim, 'dtype': out_dtype.name,
            'metric': metric, 'normalized': normalize,
            'shard_rows': shard_rows, 'shards': shard_counts}
    # atomic-ish: meta lands last, so a crashed build is an unopenable
    # directory rather than a silently short store
    meta_tmp = os.path.join(out_dir, META_NAME + '.tmp')
    with open(meta_tmp, 'w') as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(out_dir, META_NAME))
    build_s = time.perf_counter() - t0
    if tele_core.enabled():
        reg = tele_core.registry()
        reg.gauge('index/build_s').set(build_s)
        reg.gauge('index/vectors_total').set(count)
    if log is not None:
        log('index: built store `%s` (%d vectors x %d dims, %s, %s, %d '
            'shard(s), %.1fs)' % (out_dir, count, dim, out_dtype.name,
                                  metric, len(shard_counts), build_s))
    return VectorStore(out_dir)


def _text_vector_chunks(path: str, chunk_rows: int = 4096
                        ) -> Iterable[np.ndarray]:
    """Parse a ``.vectors`` text file (one space-separated vector per
    line — the evaluate/bulk export format) into float32 chunks."""
    with open(path, 'r') as f:
        rows: List[np.ndarray] = []
        for line in f:
            if not line.strip():
                continue
            rows.append(np.fromiter(line.split(), np.float32))
            if len(rows) == chunk_rows:
                yield np.stack(rows)
                rows = []
        if rows:
            yield np.stack(rows)


def build_from_vectors_file(vectors_path: str,
                            out_dir: Optional[str] = None,
                            labels: Optional[Sequence[str]] = None,
                            **kwargs) -> VectorStore:
    """Build from a ``.vectors`` text export (evaluate's
    ``--export_code_vectors`` / ``--bulk-vectors`` output). Default
    ``out_dir`` is ``<vectors_path>.vecindex``."""
    out_dir = out_dir if out_dir is not None \
        else vectors_path + STORE_SUFFIX
    return build(out_dir, _text_vector_chunks(vectors_path),
                 labels=labels, **kwargs)


def build_from_word2vec(w2v_path: str, out_dir: Optional[str] = None,
                        **kwargs) -> VectorStore:
    """Build from a word2vec TEXT export (``--export_vocab_vectors`` /
    ``--save_word2v``): header ``count dim``, then ``word v1 .. vdim``
    per line. The words become the store labels, so the index serves
    "nearest method-name" queries over the target vocab."""
    out_dir = out_dir if out_dir is not None else w2v_path + STORE_SUFFIX
    words: List[str] = []

    def chunks() -> Iterable[np.ndarray]:
        with open(w2v_path, 'r', encoding='utf-8') as f:
            header = f.readline().split()
            if len(header) != 2:
                raise ValueError(
                    '`%s` is not a word2vec text file (header must be '
                    '"count dim", got %r)' % (w2v_path, header))
            dim = int(header[1])
            rows: List[np.ndarray] = []
            for line in f:
                parts = line.rstrip('\n').split(' ')
                if len(parts) < dim + 1:
                    continue
                # the word may not contain spaces (vocab words never do);
                # the last `dim` fields are the vector
                words.append(' '.join(parts[:-dim]))
                rows.append(np.asarray(parts[-dim:], np.float32))
                if len(rows) == 4096:
                    yield np.stack(rows)
                    rows = []
            if rows:
                yield np.stack(rows)

    # `words` is late-bound: build() exhausts the chunk stream before
    # consuming the labels iterable (see build's docstring)
    return build(out_dir, chunks(), labels=words, **kwargs)
