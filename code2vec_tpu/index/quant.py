"""Quantized IVF tier: int8/PQ inverted lists, append segments, compaction.

The IVF tier (index/ivf.py) keeps the cluster-sorted rows device-resident
in the STORE dtype — f16 at best, 2 bytes/dim.  At production corpus
scale that is the binding constraint: ``HBM_BUDGET_BYTES`` caps the
resident vector count at ``budget / (2 * dim)``.  This tier swaps the
resident payload for quantized codes and scores them with the same
warm-shape discipline:

- **int8** — per-dimension symmetric scales (``scale = maxabs / 127``);
  the query is pre-scaled once per batch and the probe program is the
  same gather + f32 einsum as the IVF tier at 1 byte/dim (½ of f16).
- **pq** — product quantization of the RESIDUALS against the coarse
  centroids (IVFADC): a row's code describes ``row - centroid[list]``,
  so the codebooks spend their 256 codewords per subspace on the
  within-cluster structure instead of re-describing the cluster layout
  the coarse quantizer already captured.  The dim axis splits into
  ``M`` subspaces, each with a 256-codeword codebook trained by the
  same batched Lloyd substrate as the coarse k-means (one jitted
  update over ALL subspaces: flattened ``segment_sum`` with
  per-subspace id offsets).  A row stores one uint8 per subspace —
  ``M`` bytes/vector (dim/4 subspaces by default → 1/8 of f16).
  Scoring is asymmetric distance: ``q·row ≈ q·centroid + q·residual``
  — the first term is the coarse score the host already computed (it
  rides in as a per-candidate operand), the second a per-query LUT
  ``(Q, M, 256)`` built on device from the f32 query followed by one
  gather-accumulate over the candidate codes.  One program per (query
  bucket, probe capacity rung, re-rank depth, segment rung) — LUT
  build and gather fuse into a single warm XLA program; nothing
  recompiles per query batch.
- **re-rank** — quantized scores rank candidates; the top-R survivors
  are re-scored EXACTLY from the mmap store (``VectorStore.take``) and
  re-sorted by ``(-score, id)`` on the host.  R (``--index-rerank``)
  buys back the recall the codes gave up; the recall@10 gate
  (``index/recall_at10``) licenses the compression.

**Incremental inserts** — new vectors land in bounded append segments:
host truth (vectors + codes + assignments) persisted as versioned
``segment_%05d.npz`` sidecars under ``segments.json``, device codes in
ONE fixed-shape append buffer padded to a ``bucketed_capacity`` rung
and probed alongside the base lists by the same warm programs (candidate
positions ``>= base_rows`` select the segment buffer).  ``compact()``
folds segment truth into the store (``append_rows``) and rebuilds the
CSR by a stable re-sort of the EXISTING assignments — no k-means
rebuild — bumping the sidecar version.  With ``rerank >= candidate
count`` the merge is bit-for-rank invisible (property-tested).

Persistence: ``ivf.npz`` (shared coarse layer — an IVFIndex can open
the same store), ``quant.npz`` (kind, row-order codes, scales or
codebooks, version), ``segments.json`` + per-segment npz sidecars.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.data.packed import bucketed_capacity
from code2vec_tpu.index.ivf import (DEFAULT_ITERS, DEFAULT_NPROBE,
                                    IVF_NAME, MIN_PROBE_CAPACITY,
                                    default_clusters, kmeans)
from code2vec_tpu.index.store import VectorStore, normalize_rows
from code2vec_tpu.telemetry import core as tele_core

QUANT_NAME = 'quant.npz'
SEGMENTS_NAME = 'segments.json'
SEGMENT_PATTERN = 'segment_%05d.npz'

QUANT_KINDS = ('int8', 'pq')
PQ_CODEBOOK = 256        # codewords per subspace — codes stay uint8
DEFAULT_PQ_SUBDIM = 4    # dims per subspace when --index-pq-m is 0
DEFAULT_RERANK = 128
DEFAULT_SEGMENT_ROWS = 4096
DEFAULT_COMPACT_SEGMENTS = 8
TRAIN_SAMPLE = 1 << 16   # codebook/scale training sample cap
_ENCODE_CHUNK = 2048     # bounds the (chunk, M, 256) distance tensor


def resolve_pq_m(dim: int, m: int = 0) -> int:
    """Subspace count: the requested ``m`` clamped down to a divisor of
    ``dim`` (subspaces must tile the dim axis exactly); 0 means the
    default ``dim // 4`` — 1/8 the bytes of f16."""
    if m <= 0:
        m = max(1, dim // DEFAULT_PQ_SUBDIM)
    m = min(m, dim)
    while dim % m:
        m -= 1
    return m


# ------------------------------------------------------------ int8 codec
def train_int8(sample: np.ndarray) -> np.ndarray:
    """Per-dimension symmetric scales over a training sample:
    ``scale[d] = maxabs[d] / 127`` (floored so all-zero dims stay
    finite).  Codes then span the full int8 range per dimension."""
    sample = np.asarray(sample, np.float32)
    return np.maximum(np.abs(sample).max(axis=0), 1e-12) / 127.0


def encode_int8(vectors: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """(N, D) float -> (N, D) int8 codes (round-to-nearest, clipped)."""
    vectors = np.asarray(vectors, np.float32)
    return np.clip(np.rint(vectors / scale[None, :]),
                   -127, 127).astype(np.int8)


# -------------------------------------------------------------- pq codec
# Shared jitted kernels (module-level identity: jit caches per shape, so
# assignment/update compile once per codebook geometry, not per call).
_pq_assign_program = None
_pq_update_program = None


def _pq_assign_chunk(block, codebooks):
    """(B, M, dsub) f32 x (M, K, dsub) f32 -> (B, M) int32 nearest
    codeword per subspace (min-L2 via the max of ``x.c - 0.5*|c|^2``)."""
    global _pq_assign_program
    if _pq_assign_program is None:
        import jax
        import jax.numpy as jnp

        def assign(x, books):
            scores = (jnp.einsum('bmd,mkd->bmk', x, books)
                      - 0.5 * jnp.sum(books * books, axis=-1)[None])
            return jnp.argmax(scores, axis=-1).astype(jnp.int32)

        _pq_assign_program = jax.jit(assign)
    return _pq_assign_program(block, codebooks)


def _pq_update(x, assign, codebooks):
    """One batched Lloyd update over ALL subspaces: flattened
    ``segment_sum`` with per-subspace id offsets — one program, not M.
    Empty codewords keep their previous centroid (same contract as the
    coarse k-means)."""
    global _pq_update_program
    if _pq_update_program is None:
        import jax
        import jax.numpy as jnp

        def update(x_dev, assign_dev, books):
            n, m, dsub = x_dev.shape
            k_codebook = books.shape[1]
            offs = (jnp.arange(m, dtype=jnp.int32)
                    * k_codebook)[None, :]                 # (1, M)
            flat_ids = (assign_dev + offs).reshape(-1)
            flat_x = x_dev.reshape(n * m, dsub)
            sums = jax.ops.segment_sum(flat_x, flat_ids,
                                       num_segments=m * k_codebook)
            counts = jax.ops.segment_sum(
                jnp.ones((n * m,), jnp.float32), flat_ids,
                num_segments=m * k_codebook)
            means = (sums / jnp.maximum(counts, 1.0)[:, None]
                     ).reshape(m, k_codebook, dsub)
            occupied = (counts > 0).reshape(m, k_codebook)
            return jnp.where(occupied[..., None], means, books)

        _pq_update_program = jax.jit(update)
    return _pq_update_program(x, assign, codebooks)


def _assign_chunks(vectors: np.ndarray, codebooks: np.ndarray
                   ) -> np.ndarray:
    """(N, D) -> (N, M) int32 codeword assignments, streamed through the
    fixed ``_ENCODE_CHUNK`` so the (chunk, M, 256) distance tensor stays
    bounded and the assign kernel keeps ONE warm shape per geometry."""
    vectors = np.asarray(vectors, np.float32)
    n, dim = vectors.shape
    m, _k, dsub = codebooks.shape
    books = np.asarray(codebooks, np.float32)
    out = np.empty((n, m), np.int32)
    for start in range(0, n, _ENCODE_CHUNK):
        block = vectors[start:start + _ENCODE_CHUNK]
        rows_here = block.shape[0]
        if rows_here < _ENCODE_CHUNK:
            block = np.concatenate(
                [block, np.zeros((_ENCODE_CHUNK - rows_here, dim),
                                 np.float32)])
        codes = np.asarray(_pq_assign_chunk(  # graftlint: disable=recompile-hazard -- (chunk, M, dsub) is one warm shape per index geometry: _ENCODE_CHUNK is a module constant and (M, dsub) are fixed at build
            block.reshape(_ENCODE_CHUNK, m, dsub), books))
        out[start:start + rows_here] = codes[:rows_here]
    return out


def train_pq(sample: np.ndarray, m: int, iters: int = DEFAULT_ITERS,
             seed: int = 0) -> np.ndarray:
    """Per-subspace codebooks ``(M, K, dsub)`` float32 off the existing
    k-means substrate: batched Lloyd — chunked assignment + ONE jitted
    update across all subspaces per iteration."""
    sample = np.asarray(sample, np.float32)
    n, dim = sample.shape
    dsub = dim // m
    k_codebook = min(PQ_CODEBOOK, n)
    rng = np.random.default_rng(seed)
    rows = sample[rng.choice(n, size=k_codebook, replace=False)]
    codebooks = np.ascontiguousarray(
        rows.reshape(k_codebook, m, dsub).transpose(1, 0, 2))
    x = sample.reshape(n, m, dsub)
    for _ in range(max(1, iters)):
        assign = _assign_chunks(sample, codebooks)
        codebooks = np.asarray(_pq_update(x, assign, codebooks),
                               np.float32)
    return codebooks


def encode_pq(vectors: np.ndarray, codebooks: np.ndarray) -> np.ndarray:
    """(N, D) float -> (N, M) uint8 codes (nearest codeword per
    subspace, frozen codebooks)."""
    return _assign_chunks(vectors, codebooks).astype(np.uint8)


def coarse_probe(centroids: np.ndarray, queries: np.ndarray,
                 nprobe: int, metric: str) -> np.ndarray:
    """Top-``nprobe`` cluster ids per query (host numpy — C is tiny
    next to N; same contract as IVFIndex._coarse)."""
    q = np.asarray(queries, np.float32)
    if metric == 'cosine':
        q = normalize_rows(q)
    scores = q @ centroids.T
    return np.argsort(-scores, axis=-1, kind='stable')[:, :nprobe]


class QuantizedIVFIndex:
    """nprobe-bounded approximate k-NN over int8/PQ codes, with live
    inserts and host-exact re-rank.

    Build with ``QuantizedIVFIndex.build(store, kind=...)`` (persists
    the sidecars) or reopen with ``QuantizedIVFIndex(store)`` when
    ``quant.npz`` exists.  ``insert`` appends live vectors (queryable
    immediately, no rebuild); ``compact`` folds segments into the base
    CSR + store."""

    # graftlint: guard QuantizedIVFIndex._segments,_append_vectors,_append_codes,_append_assign,_append_row_ids,_append_labels,_append_dev,_append_capacity,_base_codes_dev,_base_rows,_store_rows,_programs,version,list_ids,offsets,list_lengths,compactions by _lock

    def __init__(self, store: VectorStore, kind: Optional[str] = None,
                 nprobe: int = DEFAULT_NPROBE,
                 rerank: int = DEFAULT_RERANK,
                 segment_rows: int = DEFAULT_SEGMENT_ROWS,
                 compact_segments: int = DEFAULT_COMPACT_SEGMENTS,
                 centroids: Optional[np.ndarray] = None,
                 list_ids: Optional[np.ndarray] = None,
                 offsets: Optional[np.ndarray] = None,
                 codes: Optional[np.ndarray] = None,
                 quant_const: Optional[np.ndarray] = None,
                 version: int = 0):
        import jax

        self.store = store
        self.metric = store.metric
        self.dim = store.dim
        self.nprobe = nprobe
        self.rerank = max(0, int(rerank))
        self.segment_rows = max(1, int(segment_rows))
        self.compact_segments = max(0, int(compact_segments))
        self._lock = threading.RLock()
        # arrays handed in = a fresh build: nothing on disk to
        # rehydrate (build() resets the sidecars it persists); arrays
        # absent = reopen path, loading sidecars + live segments
        fresh_build = codes is not None
        if centroids is None:
            centroids, list_ids, offsets = self._load_coarse(store.path)
        if codes is None:
            kind, codes, quant_const, version = self._load_quant(
                store.path, kind)
        if kind not in QUANT_KINDS:
            raise ValueError('index quant kind must be one of %s, got %r'
                             % (QUANT_KINDS, kind))
        self.kind = kind
        self.version = int(version)
        self.centroids = np.asarray(centroids, np.float32)
        self.n_clusters = self.centroids.shape[0]
        self.list_ids = np.asarray(list_ids, np.int64)
        self.offsets = np.asarray(offsets, np.int64)
        self.list_lengths = np.diff(self.offsets)
        self._quant_const = np.asarray(quant_const, np.float32)
        if kind == 'pq':
            self.pq_m, self.pq_k, self.pq_dsub = self._quant_const.shape
            if self.pq_m * self.pq_dsub != self.dim:
                raise ValueError(
                    'pq codebooks (%d subspaces x %d dims) do not tile '
                    'dim %d' % (self.pq_m, self.pq_dsub, self.dim))
        else:
            self.pq_m = self.pq_k = self.pq_dsub = 0
        codes = np.asarray(codes)
        self._code_width = int(codes.shape[1])  # bytes/vector on device
        self._base_rows = int(codes.shape[0])
        self._store_rows = store.count
        if self._base_rows != self._store_rows:
            raise ValueError(
                'quant sidecar covers %d rows but store `%s` holds %d — '
                'rebuild or compact before reopening'
                % (self._base_rows, store.path, self._store_rows))
        # empty append state (segments reload below)
        self._segments: List[dict] = []
        self._append_vectors = np.empty((0, self.dim), store.dtype)
        self._append_codes = np.empty((0, self._code_width), codes.dtype)
        self._append_assign = np.empty((0,), np.int32)
        self._append_row_ids = np.empty((0,), np.int64)
        self._append_labels: List[str] = []
        self._append_dev = None
        self._append_capacity = 0
        self._seg_entries = 0
        self.compactions = 0
        self._programs: Dict[Tuple[int, int, int, int, int], object] = {}
        # HBM budget gate + per-entry ledger registration
        # (telemetry/memory.py): same attach-boundary contract as the
        # f16 tiers, but the `index` bucket is now keyed per segment
        from code2vec_tpu.telemetry import memory as memory_lib
        sorted_codes = codes[self.list_ids]
        base_nbytes = int(sorted_codes.nbytes
                          + self._quant_const.nbytes)
        memory_lib.ledger().check_budget(
            base_nbytes,
            'index attach (quantized tier: %s, %d vectors x %d '
            'code bytes, %d clusters)'
            % (kind, self._base_rows, self._code_width, self.n_clusters))
        self.device_nbytes = 0
        self._install_base_locked(sorted_codes)
        if not fresh_build:
            self._reload_segments()

    # --------------------------------------------------------- sidecars
    @staticmethod
    def _load_coarse(path: str):
        sidecar = os.path.join(path, IVF_NAME)
        if not os.path.isfile(sidecar):
            raise FileNotFoundError(
                'no IVF sidecar at `%s` — build the quantized tier with '
                'QuantizedIVFIndex.build(store, kind=...) or '
                '--build-index --index-quant int8|pq' % sidecar)
        data = np.load(sidecar)
        return data['centroids'], data['list_ids'], data['offsets']

    @staticmethod
    def _load_quant(path: str, kind: Optional[str]):
        sidecar = os.path.join(path, QUANT_NAME)
        if not os.path.isfile(sidecar):
            raise FileNotFoundError(
                'no quantized sidecar at `%s` — build one with '
                'QuantizedIVFIndex.build(store, kind=...)' % sidecar)
        data = np.load(sidecar)
        disk_kind = str(data['kind'])
        if kind is not None and kind != disk_kind:
            raise ValueError(
                'store `%s` holds %s codes but %s was requested — '
                'rebuild with --index-quant %s'
                % (path, disk_kind, kind, kind))
        return (disk_kind, data['codes'], data['const'],
                int(data['version']))

    def _persist_quant_locked(self, codes_row_order: np.ndarray) -> None:
        """quant.npz holds the ROW-ORDER codes (compaction concatenates
        them without touching the device layout) + the frozen
        scales/codebooks + the format version; tmp-then-replace like the
        store meta."""
        path = os.path.join(self.store.path, QUANT_NAME)
        tmp = path + '.tmp.npz'
        np.savez(tmp, kind=np.asarray(self.kind),
                 codes=codes_row_order, const=self._quant_const,
                 version=np.asarray(self.version))
        os.replace(tmp, path)

    def _load_row_codes(self) -> np.ndarray:
        data = np.load(os.path.join(self.store.path, QUANT_NAME))
        return np.asarray(data['codes'])

    def _persist_manifest_locked(self) -> None:
        manifest = {'version': self.version,
                    'base_count': self._store_rows,
                    'segments': [dict(seg) for seg in self._segments]}
        path = os.path.join(self.store.path, SEGMENTS_NAME)
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(manifest, f)
        os.replace(tmp, path)

    def _reload_segments(self) -> None:
        """Rehydrate append state from the versioned segment sidecars
        (manifest + per-segment npz): a reopened index serves inserts
        that never compacted."""
        path = os.path.join(self.store.path, SEGMENTS_NAME)
        if not os.path.isfile(path):
            return
        with open(path, 'r') as f:
            manifest = json.load(f)
        with self._lock:
            version = self.version
        if int(manifest.get('version', 0)) != version:
            raise ValueError(
                'segment manifest version %s does not match quant '
                'sidecar version %d in `%s` — interrupted compaction; '
                'rebuild the index'
                % (manifest.get('version'), version, self.store.path))
        segments = list(manifest.get('segments', []))
        if not segments:
            return
        vec_parts, code_parts, assign_parts, id_parts = [], [], [], []
        labels: List[str] = []
        for seg in segments:
            data = np.load(os.path.join(self.store.path, seg['file']),
                           allow_pickle=False)
            vec_parts.append(np.asarray(data['vectors'],
                                        self.store.dtype))
            code_parts.append(np.asarray(data['codes']))
            assign_parts.append(np.asarray(data['assign'], np.int32))
            id_parts.append(np.asarray(data['row_ids'], np.int64))
            labels.extend(str(s) for s in data['labels'])
        with self._lock:
            self._segments = segments
            self._append_vectors = (np.concatenate(vec_parts)
                                    if vec_parts else
                                    self._append_vectors)
            self._append_codes = (np.concatenate(code_parts)
                                  if code_parts else self._append_codes)
            self._append_assign = np.concatenate(assign_parts)
            self._append_row_ids = np.concatenate(id_parts)
            self._append_labels = labels
            self._refresh_append_device_locked()

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, store: VectorStore, kind: str = 'pq',
              n_clusters: Optional[int] = None,
              iters: int = DEFAULT_ITERS, seed: int = 0,
              nprobe: int = DEFAULT_NPROBE,
              rerank: int = DEFAULT_RERANK, pq_m: int = 0,
              segment_rows: int = DEFAULT_SEGMENT_ROWS,
              compact_segments: int = DEFAULT_COMPACT_SEGMENTS,
              persist: bool = True, log=None) -> 'QuantizedIVFIndex':
        if kind not in QUANT_KINDS:
            raise ValueError('index quant kind must be one of %s, got %r'
                             % (QUANT_KINDS, kind))
        t0 = time.perf_counter()
        n_clusters = (n_clusters if n_clusters
                      else default_clusters(store.count))
        vectors = np.asarray(store.all_rows(), np.float32)
        centroids, assign = kmeans(vectors, n_clusters, iters=iters,
                                   seed=seed)
        n_clusters = centroids.shape[0]
        list_ids = np.argsort(assign, kind='stable').astype(np.int64)
        counts = np.bincount(assign, minlength=n_clusters)
        offsets = np.concatenate([[0],
                                  np.cumsum(counts)]).astype(np.int64)
        rng = np.random.default_rng(seed)
        pick = None
        if store.count > TRAIN_SAMPLE:
            pick = rng.choice(store.count, size=TRAIN_SAMPLE,
                              replace=False)
        if kind == 'int8':
            sample = vectors if pick is None else vectors[pick]
            quant_const = train_int8(sample)
            codes = encode_int8(vectors, quant_const)
        else:
            # IVFADC: codebooks train on (and codes describe) the
            # residuals against each row's assigned coarse centroid
            m = resolve_pq_m(store.dim, pq_m)
            residuals = vectors - centroids[assign]
            sample = residuals if pick is None else residuals[pick]
            quant_const = train_pq(sample, m, iters=iters, seed=seed)
            codes = encode_pq(residuals, quant_const)
        build_s = time.perf_counter() - t0
        if persist:
            np.savez(os.path.join(store.path, IVF_NAME),
                     centroids=centroids, list_ids=list_ids,
                     offsets=offsets)
        index = cls(store, kind=kind, nprobe=nprobe, rerank=rerank,
                    segment_rows=segment_rows,
                    compact_segments=compact_segments,
                    centroids=centroids, list_ids=list_ids,
                    offsets=offsets, codes=codes,
                    quant_const=quant_const, version=0)
        if persist:
            index._persist_quant_locked(codes)
            # a rebuild over a previously-live store resets any stale
            # segment sidecars along with the manifest
            for name in sorted(os.listdir(store.path)):
                if name.startswith('segment_') and name.endswith('.npz'):
                    os.unlink(os.path.join(store.path, name))
            index._persist_manifest_locked()
        if tele_core.enabled():
            tele_core.registry().gauge('index/build_s').set(build_s)
        if log is not None:
            log('index: quantized tier built — %s codes, %d bytes/'
                'vector (f16 would be %d), %d clusters over %d vectors '
                'in %.1fs'
                % (kind, index.bytes_per_vector,
                   2 * store.dim, n_clusters, store.count, build_s))
        return index

    # ----------------------------------------------------------- device
    def _install_base_locked(self, sorted_codes: np.ndarray) -> None:
        """Place the cluster-sorted codes + codec constants, and account
        them in the `index` bucket (keyed per resident: base codes and
        each segment register separately)."""
        import jax

        from code2vec_tpu.telemetry import memory as memory_lib
        nbytes = int(sorted_codes.nbytes + self._quant_const.nbytes)
        try:
            self._base_codes_dev = jax.device_put(sorted_codes)
            self._quant_dev = jax.device_put(self._quant_const)
        except Exception as exc:
            memory_lib.ledger().note_oom(exc, 'index.attach')
            raise
        memory_lib.ledger().register(
            'index', 'quant:%x:base' % id(self), nbytes, owner=self,
            attrs={'tier': 'quant', 'kind': self.kind,
                   'vectors': self._base_rows,
                   'code_bytes': self._code_width,
                   'clusters': self.n_clusters,
                   'version': self.version})
        self.device_nbytes += nbytes

    def _refresh_append_device_locked(self) -> None:
        """Rebuild the fixed-shape append buffer after an insert or
        compaction: codes padded to a ``bucketed_capacity`` rung (warm
        program shapes), budget-gated BEFORE placement, re-registered
        per segment so the ledger attributes segment bytes
        individually."""
        import jax

        from code2vec_tpu.telemetry import memory as memory_lib
        ledger = memory_lib.ledger()
        used = int(self._append_codes.shape[0])
        old_capacity = self._append_capacity
        for i in range(self._seg_entries):
            ledger.release('index', 'quant:%x:seg%05d' % (id(self), i))
        ledger.release('index', 'quant:%x:segslack' % id(self))
        self._seg_entries = 0
        if used == 0:
            self._append_dev = None
            self._append_capacity = 0
            self.device_nbytes -= old_capacity * self._code_width
            self._export_segment_gauges_locked()
            return
        capacity = bucketed_capacity(used, self.segment_rows)
        padded = self._append_codes
        if capacity > used:
            padded = np.concatenate(
                [padded, np.zeros((capacity - used, self._code_width),
                                  padded.dtype)])
        delta = (capacity - old_capacity) * self._code_width
        if delta > 0:
            ledger.check_budget(
                delta, 'index append segment (quantized tier: %d rows '
                       'x %d code bytes)' % (capacity, self._code_width))
        try:
            self._append_dev = jax.device_put(padded)
        except Exception as exc:
            ledger.note_oom(exc, 'index.insert')
            raise
        self._append_capacity = capacity
        self.device_nbytes += delta
        for i, seg in enumerate(self._segments):
            ledger.register(
                'index', 'quant:%x:seg%05d' % (id(self), i),
                int(seg['rows']) * self._code_width, owner=self,
                attrs={'tier': 'quant', 'segment': seg['file'],
                       'rows': int(seg['rows']),
                       'version': self.version})
        self._seg_entries = len(self._segments)
        slack = capacity - used
        if slack:
            ledger.register(
                'index', 'quant:%x:segslack' % id(self),
                slack * self._code_width, owner=self,
                attrs={'tier': 'quant', 'rows': slack,
                       'reason': 'append buffer capacity rung padding'})
        self._export_segment_gauges_locked()

    def _export_segment_gauges_locked(self) -> None:
        if not tele_core.enabled():
            return
        reg = tele_core.registry()
        reg.gauge('index/segments').set(float(len(self._segments)))
        reg.gauge('index/append_rows').set(
            float(self._append_codes.shape[0]))

    # ------------------------------------------------------- properties
    @property
    def count(self) -> int:
        """Total queryable rows: base + uncompacted appends."""
        with self._lock:
            return self._base_rows + int(self._append_codes.shape[0])

    @property
    def bytes_per_vector(self) -> int:
        """Device-resident code bytes per vector (int8: dim; pq: M)."""
        return self._code_width

    @property
    def segment_count(self) -> int:
        with self._lock:
            return len(self._segments)

    @property
    def labels(self) -> Optional[np.ndarray]:
        base = self.store.labels
        if base is None:
            return None
        with self._lock:
            if not self._append_labels:
                return base
            return np.concatenate(
                [base, np.array(self._append_labels, dtype=object)])

    # ----------------------------------------------------------- search
    def _program(self, q_bucket: int, capacity: int, r_depth: int,
                 seg_capacity: int, base_rows: int):
        # nprobe is NOT in the key (host-side fill only, like the IVF
        # tier); base_rows IS — compaction moves the base/segment
        # boundary the program bakes in, so post-compaction queries get
        # fresh programs instead of stale closures
        key = (q_bucket, capacity, r_depth, seg_capacity, base_rows)
        with self._lock:
            program = self._programs.get(key)
        if program is not None:
            return program
        import jax
        import jax.numpy as jnp

        from code2vec_tpu.ops.topk import padded_local_topk

        cosine = self.metric == 'cosine'
        kind = self.kind
        pq_m, pq_k, pq_dsub = self.pq_m, self.pq_k, self.pq_dsub

        def run(queries, quant_const, base_codes, seg_codes, cand,
                cand_offsets):
            q = queries.astype(jnp.float32)
            if cosine:
                norms = jnp.linalg.norm(q, axis=-1, keepdims=True)
                q = q / jnp.where(norms > 0, norms, 1.0)
            base_part = jnp.take(
                base_codes, jnp.clip(cand, 0, base_rows - 1), axis=0)
            if seg_capacity:
                seg_part = jnp.take(
                    seg_codes,
                    jnp.clip(cand - base_rows, 0, seg_capacity - 1),
                    axis=0)
                rows = jnp.where((cand >= base_rows)[..., None],
                                 seg_part, base_part)
            else:
                rows = base_part                       # (Q, cap, W)
            if kind == 'int8':
                scores = jnp.einsum('qd,qcd->qc',
                                    q * quant_const[None, :],
                                    rows.astype(jnp.float32))
            else:
                # asymmetric distance over residual codes: the coarse
                # term q.centroid arrives per candidate (cand_offsets,
                # host-filled from the coarse scores), the residual
                # term is a per-query LUT (Q, M, 256) built on device
                # + a flat gather-accumulate — fused with the top-k
                lut = jnp.einsum(
                    'qmd,mkd->qmk',
                    q.reshape(q.shape[0], pq_m, pq_dsub), quant_const)
                flat_lut = lut.reshape(q.shape[0], pq_m * pq_k)
                idx = (rows.astype(jnp.int32)
                       + (jnp.arange(pq_m, dtype=jnp.int32)
                          * pq_k)[None, None, :])     # (Q, cap, M)

                def gather_one(flat_q, idx_q):
                    return jnp.take(flat_q, idx_q,
                                    axis=0).sum(axis=-1)

                scores = cand_offsets + jax.vmap(gather_one)(flat_lut,
                                                             idx)
            scores = jnp.where(cand >= 0, scores, -jnp.inf)
            return padded_local_topk(scores, r_depth)

        program = jax.jit(run)
        with self._lock:
            self._programs[key] = program
        return program

    def search(self, queries: np.ndarray, k: int,
               nprobe: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(Q, D) queries -> ((Q, k) scores, (Q, k) ORIGINAL row ids).
        Candidates come from the probed base lists PLUS any append
        segments; scores are quantized unless ``rerank > 0``, in which
        case the top-R candidates are re-scored exactly from the mmap
        store.  −inf/−1 sentinels pad queries with fewer than ``k``
        candidates."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        n = queries.shape[0]
        t0 = time.perf_counter()
        with self._lock:
            base_rows = self._base_rows
            list_ids = self.list_ids
            offsets = self.offsets
            lengths = self.list_lengths
            append_assign = self._append_assign
            append_row_ids = self._append_row_ids
            append_used = int(append_assign.shape[0])
            seg_capacity = self._append_capacity if append_used else 0
            base_codes_dev = self._base_codes_dev
            quant_dev = self._quant_dev
            append_dev = (self._append_dev if seg_capacity
                          else base_codes_dev)
        nprobe = min(self.n_clusters,
                     nprobe if nprobe is not None else self.nprobe)
        qn = queries
        if self.metric == 'cosine':
            qn = normalize_rows(queries)
        # coarse scores serve double duty: probe selection AND (pq) the
        # per-candidate q.centroid offset of the residual decomposition
        coarse = qn @ self.centroids.T                   # (Q, C)
        probe = np.argsort(-coarse, axis=-1,
                           kind='stable')[:, :nprobe]
        starts = offsets[probe]
        lens = lengths[probe]
        totals = lens.sum(axis=1)
        matches: List[np.ndarray] = []
        if append_used:
            for row in range(n):
                matches.append(
                    np.nonzero(np.isin(append_assign, probe[row]))[0])
            totals = totals + np.array([m.shape[0] for m in matches],
                                       totals.dtype)
        capacity = bucketed_capacity(int(totals.max(initial=1)),
                                     MIN_PROBE_CAPACITY)
        cand = np.full((n, capacity), -1, np.int64)
        cand_offsets = np.zeros((n, capacity), np.float32)
        for row in range(n):
            pos = 0
            for cluster, start, length in zip(probe[row], starts[row],
                                              lens[row]):
                cand[row, pos:pos + length] = np.arange(start,
                                                        start + length)
                cand_offsets[row, pos:pos + length] = coarse[row,
                                                             cluster]
                pos += length
            if append_used and matches[row].shape[0]:
                hit = matches[row]
                cand[row, pos:pos + hit.shape[0]] = base_rows + hit
                cand_offsets[row, pos:pos + hit.shape[0]] = \
                    coarse[row, append_assign[hit]]
        r_depth = min(capacity,
                      max(k, self.rerank) if self.rerank else k)
        from code2vec_tpu.index.exact import (DEFAULT_QUERY_BUCKETS,
                                              _pick_bucket)
        q_bucket = _pick_bucket(n, DEFAULT_QUERY_BUCKETS)
        if q_bucket != n:
            queries_in = np.concatenate(
                [queries,
                 np.zeros((q_bucket - n, self.dim), np.float32)])
            cand = np.concatenate(
                [cand, np.full((q_bucket - n, capacity), -1, np.int64)])
            cand_offsets = np.concatenate(
                [cand_offsets,
                 np.zeros((q_bucket - n, capacity), np.float32)])
        else:
            queries_in = queries
        program = self._program(q_bucket, capacity, r_depth,
                                seg_capacity, base_rows)
        values, positions = program(queries_in, quant_dev,
                                    base_codes_dev, append_dev,
                                    cand.astype(np.int32),
                                    cand_offsets)
        values = np.asarray(values)[:n]
        positions = np.asarray(positions)[:n]
        # positions index the (Q, capacity) candidate axis -> combined
        # position space: [0, base_rows) is the cluster-sorted base,
        # [base_rows, base_rows+append) the insert-ordered segments
        comb = np.take_along_axis(
            cand[:n], np.maximum(positions, 0).astype(np.int64),
            axis=-1)
        base_ids = list_ids[np.clip(comb, 0, base_rows - 1)]
        if append_used:
            app_ids = append_row_ids[
                np.clip(comb - base_rows, 0, append_used - 1)]
            ids = np.where(comb >= base_rows, app_ids, base_ids)
        else:
            ids = base_ids
        ids = np.where((positions >= 0) & (comb >= 0), ids, -1)
        if self.rerank:
            values, ids = self._rerank_exact(queries, values, ids, k)
        else:
            values, ids = values[:, :k], ids[:, :k]
        if values.shape[1] < k:
            pad = k - values.shape[1]
            values = np.concatenate(
                [values, np.full((n, pad), -np.inf, values.dtype)],
                axis=1)
            ids = np.concatenate(
                [ids, np.full((n, pad), -1, ids.dtype)], axis=1)
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('index/queries_total').inc(n)
            reg.timer('index/query_latency_ms').record(
                time.perf_counter() - t0)
            reg.gauge('index/probe_fanout').set(float(totals.mean()))
        return values, ids

    def _rerank_exact(self, queries: np.ndarray, values: np.ndarray,
                      ids: np.ndarray, k: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact re-scoring of the quantized top-R: candidate rows come
        back from the mmap store (``VectorStore.take``) or the host
        segment copies, scores recompute in f32, and the final order is
        the deterministic ``(-score, id)`` sort — bit-for-rank
        reproducible whenever R covers the candidate set."""
        q = np.asarray(queries, np.float32)
        if self.metric == 'cosine':
            q = normalize_rows(q)
        n, r_depth = ids.shape
        with self._lock:
            store_rows = self._store_rows
            append_vectors = self._append_vectors
        flat = ids.ravel()
        vecs = np.zeros((flat.shape[0], self.dim), np.float32)
        base_sel = (flat >= 0) & (flat < store_rows)
        app_sel = flat >= store_rows
        if base_sel.any():
            vecs[base_sel] = np.asarray(self.store.take(flat[base_sel]),
                                        np.float32)
        if app_sel.any():
            vecs[app_sel] = np.asarray(
                append_vectors[flat[app_sel] - store_rows], np.float32)
        scores = np.einsum('qd,qrd->qr', q,
                           vecs.reshape(n, r_depth, self.dim))
        scores = np.where(ids >= 0, scores, -np.inf)
        order = np.lexsort((ids, -scores), axis=-1)[:, :k]
        return (np.take_along_axis(scores, order, axis=-1),
                np.take_along_axis(ids, order, axis=-1))

    def warmup(self, k: int, nprobe: Optional[int] = None) -> int:
        """Eagerly compile the probe program per query bucket at the
        CURRENT capacity rungs (same warm-ladder contract as the exact
        tier's warmup).  Returns the number of buckets warmed."""
        from code2vec_tpu.index.exact import DEFAULT_QUERY_BUCKETS
        warmed = 0
        for bucket in DEFAULT_QUERY_BUCKETS:
            self.search(np.zeros((bucket, self.dim), np.float32), k,
                        nprobe=nprobe)
            warmed += 1
        return warmed

    # ---------------------------------------------------------- inserts
    def insert(self, vectors: np.ndarray,
               labels: Optional[Sequence[str]] = None) -> np.ndarray:
        """Append live vectors: encoded with the FROZEN codecs, assigned
        to the existing coarse lists, persisted as versioned segment
        sidecars, and queryable immediately (no rebuild).  Returns the
        assigned global row ids.  An empty batch records an empty
        segment (format drills) and allocates nothing.  Triggers
        ``compact()`` when the segment count passes
        ``compact_segments`` (0 disables auto-compaction)."""
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        if vectors.size and vectors.shape[1] != self.dim:
            raise ValueError('inserted vectors must be (n, %d), got %r'
                             % (self.dim, vectors.shape))
        n_new = int(vectors.shape[0])
        if self.store.normalized:
            vectors = normalize_rows(vectors)
        canonical = np.ascontiguousarray(vectors, self.store.dtype)
        # encode from the canonical (store-dtype) rows so pre- and
        # post-compaction scoring see bit-identical inputs
        encode_from = np.asarray(canonical, np.float32)
        if n_new:
            assign = np.argmax(
                encode_from @ self.centroids.T, axis=-1).astype(np.int32)
            if self.kind == 'int8':
                codes = encode_int8(encode_from, self._quant_const)
            else:
                codes = encode_pq(
                    encode_from - self.centroids[assign],
                    self._quant_const)
        else:
            assign = np.empty((0,), np.int32)
            codes = np.empty((0, self._code_width),
                             np.int8 if self.kind == 'int8' else np.uint8)
        row_labels = ([str(item) for item in labels]
                      if labels is not None else [''] * n_new)
        if len(row_labels) != n_new:
            raise ValueError('%d labels for %d inserted vectors'
                             % (len(row_labels), n_new))
        with self._lock:
            next_id = self._store_rows + self._append_row_ids.shape[0]
            row_ids = np.arange(next_id, next_id + n_new, dtype=np.int64)
            # page the batch into fixed-size segments (a batch larger
            # than segment_rows spans several); an empty batch is one
            # empty segment
            cursor = 0
            while True:
                rows_here = min(self.segment_rows, n_new - cursor)
                seg_file = SEGMENT_PATTERN % len(self._segments)
                seg_path = os.path.join(self.store.path, seg_file)
                tmp = seg_path + '.tmp.npz'
                sl = slice(cursor, cursor + rows_here)
                np.savez(tmp, vectors=canonical[sl], codes=codes[sl],
                         assign=assign[sl], row_ids=row_ids[sl],
                         labels=np.asarray(row_labels[sl], dtype=str))
                os.replace(tmp, seg_path)
                self._segments.append({'file': seg_file,
                                       'rows': rows_here})
                cursor += rows_here
                if cursor >= n_new:
                    break
            self._persist_manifest_locked()
            self._append_vectors = np.concatenate(
                [self._append_vectors, canonical])
            self._append_codes = np.concatenate(
                [self._append_codes, codes])
            self._append_assign = np.concatenate(
                [self._append_assign, assign])
            self._append_row_ids = np.concatenate(
                [self._append_row_ids, row_ids])
            self._append_labels.extend(row_labels)
            self._refresh_append_device_locked()
            if tele_core.enabled():
                tele_core.registry().counter(
                    'index/inserts_total').inc(n_new)
            if (self.compact_segments
                    and len(self._segments) > self.compact_segments):
                self.compact()
        return row_ids

    def compact(self) -> int:
        """Fold append segments into the base CSR + store: appended
        vectors land as new store shards (``append_rows``), the
        inverted lists rebuild by a stable re-sort of the EXISTING
        assignments (no k-means rebuild), the sidecar version bumps, and
        the segment files retire.  Returns the rows compacted.  Holds
        the index lock throughout — concurrent inserts/searches block
        and land against the compacted index."""
        t0 = time.perf_counter()
        with self._lock:
            compacted = int(self._append_codes.shape[0])
            from code2vec_tpu.telemetry import memory as memory_lib
            ledger = memory_lib.ledger()
            if compacted:
                has_labels = self.store.labels is not None
                self.store.append_rows(
                    self._append_vectors,
                    labels=(self._append_labels if has_labels
                            else None),
                    canonical=True)
                row_codes = np.concatenate(
                    [self._load_row_codes(), self._append_codes])
                base_assign = np.empty((self._base_rows,), np.int64)
                base_assign[self.list_ids] = np.repeat(
                    np.arange(self.n_clusters), self.list_lengths)
                assign_all = np.concatenate(
                    [base_assign,
                     self._append_assign.astype(np.int64)])
                self.list_ids = np.argsort(
                    assign_all, kind='stable').astype(np.int64)
                counts = np.bincount(assign_all,
                                     minlength=self.n_clusters)
                self.offsets = np.concatenate(
                    [[0], np.cumsum(counts)]).astype(np.int64)
                self.list_lengths = np.diff(self.offsets)
                self._base_rows = int(row_codes.shape[0])
                self._store_rows = self.store.count
            else:
                row_codes = None
            self.version += 1
            if row_codes is not None:
                self._persist_quant_locked(row_codes)
                np.savez(os.path.join(self.store.path, IVF_NAME),
                         centroids=self.centroids,
                         list_ids=self.list_ids, offsets=self.offsets)
            for seg in self._segments:
                try:
                    os.unlink(os.path.join(self.store.path,
                                           seg['file']))
                except OSError:
                    pass
            self._segments = []
            self._persist_manifest_locked()
            self._append_vectors = np.empty((0, self.dim),
                                            self.store.dtype)
            self._append_codes = np.empty(
                (0, self._code_width), self._append_codes.dtype)
            self._append_assign = np.empty((0,), np.int32)
            self._append_row_ids = np.empty((0,), np.int64)
            self._append_labels = []
            if row_codes is not None:
                sorted_codes = row_codes[self.list_ids]
                ledger.release('index', 'quant:%x:base' % id(self))
                self.device_nbytes = 0
                ledger.check_budget(
                    int(sorted_codes.nbytes + self._quant_const.nbytes),
                    'index compaction (quantized tier: %d vectors x %d '
                    'code bytes)'
                    % (self._base_rows, self._code_width))
                self._install_base_locked(sorted_codes)
                # the base/segment boundary moved: cached programs bake
                # the old base_rows into their closures
                self._programs.clear()
            self._refresh_append_device_locked()
            self.compactions += 1
            if tele_core.enabled():
                reg = tele_core.registry()
                reg.counter('index/compactions_total').inc()
                reg.gauge('index/compact_s').set(
                    time.perf_counter() - t0)
        return compacted
