"""Neighbor queries as a serving workload: build/load/query orchestration.

Three entry points, all reachable from the CLI (INDEX.md runbook):

- ``build_index(model, config)`` — ``--build-index SOURCE``: build a
  store (+ optional IVF sidecar) from a ``.c2v`` corpus (streamed
  through the vectors-tier predict program, no text round-trip), a
  ``.vectors`` text export, or a word2vec text file (vocab-embedding
  nearest-NAME queries).
- ``load_index(path, ...)`` — open a built index at its configured tier
  (IVF when the sidecar exists or is asked for; exact otherwise),
  warm-compiled for the configured k.
- ``query_neighbors_file(model, config)`` — ``--query-neighbors
  FILE.c2v``: stream every kept example through the vectors tier + index
  lookup and emit one JSONL record per query to
  ``FILE.neighbors.jsonl``.

The interactive composition — "paste a method, get the K most similar
corpus methods in one warm round-trip" — lives on the serving engine:
``ServingEngine.attach_index`` + ``submit_neighbors``
(serving/engine.py), which routes the vectors tier through the same
micro-batching dispatcher as every other tier.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, NamedTuple, Optional

import numpy as np

from code2vec_tpu.index import store as store_lib
from code2vec_tpu.index.exact import ExactIndex
from code2vec_tpu.index.ivf import IVFIndex, measure_recall
from code2vec_tpu.telemetry import core as tele_core


class NeighborResult(NamedTuple):
    """Neighbors of ONE query, rank order. ``indices`` are store row
    ids (−1 sentinels when the probed lists held fewer than k
    candidates); ``labels`` aligns with ``indices`` when the store has
    labels, else None."""
    indices: np.ndarray          # (k,) int
    scores: np.ndarray           # (k,) float32
    labels: Optional[List[str]] = None


def neighbors_from_search(values: np.ndarray, indices: np.ndarray,
                          labels) -> List[NeighborResult]:
    """Per-query ``NeighborResult`` rows from a batched search output."""
    out = []
    for row_values, row_indices in zip(values, indices):
        row_labels = None
        if labels is not None:
            row_labels = [str(labels[i]) if i >= 0 else ''
                          for i in row_indices]
        out.append(NeighborResult(indices=row_indices,
                                  scores=row_values,
                                  labels=row_labels))
    return out


def _looks_like_word2vec(path: str) -> bool:
    """A word2vec text export starts with a `count dim` header."""
    try:
        with open(path, 'r', encoding='utf-8', errors='replace') as f:
            parts = f.readline().split()
        return len(parts) == 2 and all(p.isdigit() for p in parts)
    except OSError:
        return False


def build_index(model, config, source: Optional[str] = None,
                out_dir: Optional[str] = None):
    """Build a store at ``out_dir`` (default ``<source>.vecindex``) from
    ``source``, add the IVF sidecar when ``INDEX_KIND='ivf'`` (reporting
    measured recall@10 vs the exact tier on a held-out sample of store
    rows), and return the loaded index."""
    source = source if source is not None else config.BUILD_INDEX_FROM
    out_dir = (out_dir if out_dir is not None
               else (config.INDEX_PATH
                     or source + store_lib.STORE_SUFFIX))
    log = config.log
    kwargs = dict(dtype=config.VECTORS_DTYPE, metric=config.INDEX_METRIC,
                  log=log)
    if source.endswith('.c2v'):
        if model is None:
            raise ValueError('building an index from a .c2v corpus needs '
                             'a model (the vectors tier embeds it)')
        from code2vec_tpu.serving import bulk
        labels: List[str] = []

        def chunks():
            for vectors, batch_labels in bulk.iter_code_vector_batches(
                    model, source, with_labels=True):
                if batch_labels is not None:
                    labels.extend(str(label) for label in batch_labels)
                yield vectors

        # stream the generator straight through: the builder writes all
        # chunks BEFORE consuming the labels iterable, so `labels` is
        # complete by then and no corpus-sized list ever exists in RAM
        store = store_lib.build(out_dir, chunks(), labels=labels,
                                **kwargs)
    elif _looks_like_word2vec(source):
        store = store_lib.build_from_word2vec(source, out_dir, **kwargs)
    else:
        store = store_lib.build_from_vectors_file(source, out_dir,
                                                  **kwargs)
    index = _open_tier(store, config, model)
    if isinstance(index, IVFIndex) or config.INDEX_QUANT:
        sample = min(256, store.count)
        rng = np.random.default_rng(0)
        queries = np.asarray(
            store.all_rows()[rng.choice(store.count, sample,
                                        replace=False)], np.float32)
        exact = ExactIndex(store, mesh=_mesh_of(model))
        recall = measure_recall(index, exact, queries, k=10)
        tier = config.INDEX_QUANT or 'IVF'
        log('index: %s recall@10 = %.3f vs exact on %d held-out store '
            'rows (nprobe=%d of %d lists)'
            % (tier, recall, sample, index.nprobe, index.n_clusters))
        if config.INDEX_QUANT:
            log('index: quantized tier serves %d bytes/vector on '
                'device (f16 rows would be %d)'
                % (index.bytes_per_vector, 2 * store.dim))
    log('index: ready at `%s` (%s, %d vectors, metric=%s, dtype=%s)'
        % (out_dir, config.INDEX_QUANT or config.INDEX_KIND,
           store.count, store.metric, store.dtype.name))
    return index


def _mesh_of(model):
    return model.mesh if model is not None else None


def _open_tier(store, config, model=None):
    """Store -> index object at the configured tier. IVF and the
    quantized tier reuse their persisted sidecars when present, else
    build (and persist) them; exact never silently upgrades."""
    from code2vec_tpu.index.ivf import DEFAULT_NPROBE, IVF_NAME
    if config.INDEX_QUANT:
        from code2vec_tpu.index.quant import (QUANT_NAME,
                                              QuantizedIVFIndex)
        nprobe = config.INDEX_NPROBE or DEFAULT_NPROBE
        kwargs = dict(nprobe=nprobe, rerank=config.INDEX_RERANK,
                      segment_rows=config.INDEX_SEGMENT_ROWS,
                      compact_segments=config.INDEX_COMPACT_SEGMENTS)
        if os.path.isfile(os.path.join(store.path, QUANT_NAME)):
            index = QuantizedIVFIndex(store, kind=config.INDEX_QUANT,
                                      **kwargs)
        else:
            index = QuantizedIVFIndex.build(
                store, kind=config.INDEX_QUANT,
                n_clusters=config.INDEX_CLUSTERS or None,
                pq_m=config.INDEX_PQ_M, log=config.log, **kwargs)
        index.warmup(config.INDEX_NEIGHBORS_K)
        return index
    if config.INDEX_KIND == 'ivf':
        nprobe = config.INDEX_NPROBE or DEFAULT_NPROBE
        if os.path.isfile(os.path.join(store.path, IVF_NAME)):
            return IVFIndex(store, nprobe=nprobe)
        return IVFIndex.build(
            store, n_clusters=config.INDEX_CLUSTERS or None,
            nprobe=nprobe, log=config.log)
    return ExactIndex(store, mesh=_mesh_of(model)).warmup(
        config.INDEX_NEIGHBORS_K)


def load_index(path: str, config, model=None):
    """Open a built index directory at the configured tier (IVF builds
    and persists its sidecar on first open; exact warm-compiles at
    ``INDEX_NEIGHBORS_K``)."""
    return _open_tier(store_lib.VectorStore(path), config, model)


def query_neighbors_file(model, config, index=None,
                         corpus_path: Optional[str] = None,
                         output_path: Optional[str] = None):
    """Batch neighbor queries: stream ``corpus_path`` (default
    ``QUERY_NEIGHBORS_PATH``) through the vectors tier and the index,
    writing one JSONL record per kept example to ``output_path``
    (default ``<corpus>.neighbors.jsonl``)::

        {"name": "do|thing", "neighbors":
            [{"rank": 0, "row": 17, "score": 0.93, "label": "do|other"},
             ...]}

    Returns ``(n_queries, output_path)``."""
    from code2vec_tpu.serving import bulk
    corpus_path = (corpus_path if corpus_path is not None
                   else config.QUERY_NEIGHBORS_PATH)
    output_path = (output_path if output_path is not None
                   else corpus_path + '.neighbors.jsonl')
    if index is None:
        index = load_index(config.INDEX_PATH, config, model)
    k = config.INDEX_NEIGHBORS_K
    total = 0
    t0 = time.perf_counter()
    with open(output_path, 'w') as out:
        for vectors, batch_labels in bulk.iter_code_vector_batches(
                model, corpus_path, with_labels=True):
            values, indices = index.search(vectors, k)
            results = neighbors_from_search(values, indices, index.labels)
            for r, result in enumerate(results):
                record = {
                    'name': (str(batch_labels[r])
                             if batch_labels is not None else ''),
                    'neighbors': [
                        {'rank': rank, 'row': int(row),
                         'score': float(score),
                         **({'label': result.labels[rank]}
                            if result.labels is not None else {})}
                        for rank, (row, score) in enumerate(
                            zip(result.indices, result.scores))
                        if row >= 0]}
                out.write(json.dumps(record) + '\n')
            total += len(results)
    elapsed = time.perf_counter() - t0
    if tele_core.enabled():
        tele_core.registry().gauge('index/queries_per_sec').set(
            total / max(elapsed, 1e-9))
    config.log('index: %d neighbor queries -> `%s` (%d queries/sec)'
               % (total, output_path, int(total / max(elapsed, 1e-9))))
    return total, output_path
