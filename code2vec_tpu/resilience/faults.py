"""Deterministic fault injection: the testing backbone of the resilience
layer (ROBUSTNESS.md).

A run is armed with a spec — ``Config.FAULT_INJECT`` or the
``FAULT_INJECT`` environment variable — of comma-separated
``<point>@<trigger>=<n>`` entries:

    nan_loss@step=120,sigterm@step=50
    hang_input@step=30
    corrupt_snapshot@save=2

Each *fault point* is a named site in production code that calls
``maybe_fire(<point>)``; the spec decides WHEN that site fires (at most
once per configured plan).  The trigger count is either the explicit
``step=`` value the site passes (the trainer passes its global step
counter) or, for sites with no natural step, the number of times the
site has been reached (``hang_input`` counts batches, ``corrupt_snapshot``
counts snapshot saves).  The trigger key name (``step`` / ``save`` / …)
is documentation for humans — the plan only keeps the integer.

A trigger may also be a **fire window** ``<lo>..<hi>`` (inclusive):

    extractor_crash@call=0..2,slow_dispatch@req=0..3

the point then fires at EVERY trigger count inside the window — the
multi-shot shape breaker/overload drills need (a circuit breaker trips
on K *consecutive* crashes; one crash proves nothing) — and is done once
the count passes ``hi``.  A single ``<n>`` keeps the original semantics:
single-shot, ``>=``-matched so a resumed run that skipped the exact
count still fires once.

What happens on fire is implemented AT the site (poison the loss, kill
the process, sleep, truncate the artifact): the harness only decides
when, so the injected failure exercises the exact code path a real one
would.

``FAULT_POINTS`` is the catalog every site name must come from —
``scripts/check_fault_points.py`` lints call sites against it (the same
pattern as the metric-schema lint), so a typo'd point name fails tier-1
instead of silently never firing.

Dependency-free (stdlib only) and thread-safe: sites fire from the
training thread, the reader prefetch thread, and the checkpoint path.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)

#: every fault point a ``maybe_fire`` site may name, with where it lives
#: and what firing does there.  Keep ROBUSTNESS.md's table in sync — the
#: lint checks the doc mentions every name.
FAULT_POINTS: Dict[str, str] = {
    'nan_loss': 'training/trainer.py hot loop: poison the triggering '
                "step's loss with NaN (exercises the divergence guard).",
    'sigterm': 'training/trainer.py hot loop: deliver SIGTERM to this '
               'process once the step counter reaches the trigger '
               '(exercises preemption-safe shutdown).',
    'hang_input': 'data/reader.py batch stream: block the input pipeline '
                  'indefinitely at the triggering batch (exercises the '
                  'hang watchdog).',
    'corrupt_snapshot': 'checkpoints.py: truncate the files of the '
                        'just-written step snapshot (exercises the '
                        'restore fallback).',
    'slow_dispatch': 'serving/engine.py dispatcher: sleep '
                     'SLOW_DISPATCH_SECONDS before dispatching the '
                     'triggering micro-batch (exercises admission '
                     'control: queue bound, shedding, deadline expiry).',
    'slow_step': 'training/trainer.py hot loop: sleep SLOW_STEP_SECONDS '
                 'inside the triggering train step(s) — a sustained '
                 'per-step stall shaped like a degraded input stage or '
                 'a throttled device (exercises the step-time anomaly '
                 'watchdog and its profiler auto-capture; use a '
                 'lo..hi window for the sustained shape it detects).',
    'extractor_crash': 'serving/extractor_bridge.py pool call: the '
                       'triggering extractor invocation raises '
                       'ExtractorCrash as if the subprocess died '
                       '(exercises retry-with-backoff and the circuit '
                       'breaker).',
    'reject_all': 'serving/engine.py admission: the triggering submit '
                  'calls are shed with EngineOverloaded regardless of '
                  'queue state (exercises client fail-fast handling).',
    'kill_worker': 'serving/mesh.py worker serve loop: SIGKILL this '
                   'replica worker process as the triggering dispatch '
                   'arrives — mid-batch, so the parent holds it in '
                   'flight (exercises crash-safe redispatch and '
                   'supervised restart).',
    'kill_worker_after_execute': 'serving/mesh.py worker serve loop: '
                                 'SIGKILL this replica worker AFTER '
                                 'the triggering dispatch executed on '
                                 'device (its finished spans ship on '
                                 'a heartbeat first) but BEFORE the '
                                 'result frame — the crash shape where '
                                 'device work was done and lost, so a '
                                 'redispatched request\'s stitched '
                                 'trace must show BOTH incarnations\' '
                                 'device-execute spans.',
    'drop_heartbeat': 'serving/mesh.py worker heartbeat thread: the '
                      'triggering heartbeat(s) are silently skipped, '
                      'the drilled shape of a hung-but-connected '
                      'worker (exercises the liveness monitor, which '
                      'the dispatch breaker cannot replace).',
    'partition': 'serving/mesh.py parent receiver: the triggering '
                 'frame(s) from the worker are dropped as if the '
                 'network partitioned — results AND heartbeats vanish '
                 'while both endpoints stay up (exercises liveness '
                 'detection and redispatch of the blackholed batch).',
    'spawn_fail': 'serving/mesh.py _spawn_worker: the triggering spawn '
                  'attempt raises before the worker process starts — '
                  'the shape of an exec/resource failure on the host '
                  '(exercises restart-budget accounting and autoscaler '
                  'scale-up failure handling: a failed scale-up must '
                  'not wedge the control loop or leak a slot).',
    'adopt_stall': 'serving/mesh.py worker startup (also reached by '
                   'scripts/mesh_worker.py): the triggering worker '
                   'dials in but stalls ADOPT_STALL_SECONDS before '
                   'sending its ready frame — the shape of an adopted '
                   'worker wedging mid-cold-start (exercises the '
                   'adoption timeout: the dial-in is dropped typed '
                   'instead of wedging the adoption loop).',
}

#: how long a fired ``hang_input`` blocks.  Long enough that only a
#: watchdog abort ends the run, short enough that a leaked daemon thread
#: in a test process eventually unwinds.
HANG_SECONDS = 600.0

#: how long a fired ``slow_dispatch`` stalls the serving dispatcher.
#: Long enough that an open-loop burst deterministically outruns the
#: queue bound, short enough that a windowed drill stays inside test
#: budgets.
SLOW_DISPATCH_SECONDS = 0.25

#: how long a fired ``adopt_stall`` delays a worker's ready frame.
#: Longer than the adoption loop's ready timeout in the drills (which
#: pin it down via config), short enough that the stalled worker
#: process unwinds inside a test budget.
ADOPT_STALL_SECONDS = 20.0

#: how long a fired ``slow_step`` stalls one hot-loop train step.
#: Far past any smoke-model step's median + GOODPUT_ANOMALY_SIGMA
#: robust deviations, so a windowed drill deterministically trips the
#: anomaly watchdog, while a 3-step sustain window costs <0.5s.
SLOW_STEP_SECONDS = 0.12


def parse_spec(spec: str) -> Dict[str, object]:
    """``'nan_loss@step=120,sigterm@step=50'`` -> {point: trigger}.

    A trigger is an ``int`` (single-shot, ``>=``-matched) or a
    ``(lo, hi)`` tuple for a ``lo..hi`` fire window (multi-shot,
    inclusive).  Raises ``ValueError`` on an unknown fault point or
    malformed entry — a typo'd injection spec must fail the run at
    startup, not silently inject nothing.
    """
    plan: Dict[str, object] = {}
    for entry in (spec or '').split(','):
        entry = entry.strip()
        if not entry:
            continue
        try:
            point, trigger = entry.split('@', 1)
            _key, value = trigger.split('=', 1)
            if '..' in value:
                lo_text, hi_text = value.split('..', 1)
                at: object = (int(lo_text), int(hi_text))
            else:
                at = int(value)
        except ValueError:
            raise ValueError(
                'FAULT_INJECT entry %r is not <point>@<trigger>=<int> or '
                '<point>@<trigger>=<lo>..<hi> (e.g. nan_loss@step=120, '
                'extractor_crash@call=0..2)' % entry)
        if point not in FAULT_POINTS:
            raise ValueError(
                'FAULT_INJECT names unknown fault point %r; known points: '
                '%s (resilience/faults.py)' % (point,
                                               ', '.join(sorted(FAULT_POINTS))))
        if isinstance(at, tuple):
            if at[0] < 0 or at[1] < at[0]:
                raise ValueError(
                    'FAULT_INJECT entry %r: fire window must be '
                    '0 <= lo <= hi' % entry)
        elif at < 0:
            raise ValueError(
                'FAULT_INJECT entry %r: trigger count must be >= 0' % entry)
        plan[point] = at
    return plan


class FaultPlan:
    """The armed plan: which points fire, and at which trigger count.

    A single-count point fires AT MOST ONCE per plan (deterministic
    single-shot faults); ``>=`` matching makes a fault whose exact count
    was skipped (a resumed run starting past it) still fire at the next
    opportunity.  A ``(lo, hi)`` fire-window point fires at every
    trigger count inside the window and is done once the count passes
    ``hi``.
    """

    # fault sites probe from the trainer thread, the input pipeline, and
    # tests' drill threads (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard FaultPlan._at,_site_counts,_fired by _lock
    def __init__(self, plan: Dict[str, object]):
        self._at = dict(plan)
        self._site_counts: Dict[str, int] = {}
        self._fired: set = set()
        self._lock = threading.Lock()

    def maybe_fire(self, point: str, step: Optional[int] = None) -> bool:
        with self._lock:
            at = self._at.get(point)
            if at is None or point in self._fired:
                return False
            if step is None:
                step = self._site_counts.get(point, 0)
                self._site_counts[point] = step + 1
            if isinstance(at, tuple):
                lo, hi = at
                if step > hi:
                    self._fired.add(point)  # window passed: done
                    return False
                if step < lo:
                    return False
                # inside the window: fire, stay armed for the next count
            else:
                if step < at:
                    return False
                self._fired.add(point)
        logger.warning('FAULT_INJECT: firing %r at trigger count %d',
                       point, step)
        from code2vec_tpu.telemetry import core
        if core.enabled():
            core.registry().counter('resilience/faults_fired_total').inc()
        return True

    def fired(self, point: str) -> bool:
        with self._lock:
            return point in self._fired


# Process-global plan, like the telemetry registry: fault points live in
# layers (reader, checkpoints) that have no config handle.  None (the
# default) keeps every site at a single attribute read.
_PLAN: Optional[FaultPlan] = None


def configure(spec: str) -> Optional[FaultPlan]:
    """Arm (or clear, for an empty spec) the process-global plan.  Called
    once per run from ``Trainer.__init__`` with the resolved
    config/env spec; re-configuring resets fired state, so each run's
    injections are deterministic regardless of process reuse (tests)."""
    global _PLAN
    plan = parse_spec(spec)
    _PLAN = FaultPlan(plan) if plan else None
    if _PLAN is not None:
        logger.warning('FAULT_INJECT armed: %s',
                       ', '.join('%s@%d..%d' % (p, n[0], n[1])
                                 if isinstance(n, tuple) else
                                 '%s@%d' % (p, n)
                                 for p, n in sorted(plan.items())))
    return _PLAN


def maybe_fire(point: str, step: Optional[int] = None) -> bool:
    """True when the armed plan says fault ``point`` fires now.  The
    caller implements the fault.  Assert-level cheap when no plan is
    armed (the production default)."""
    if _PLAN is None:
        return False
    assert point in FAULT_POINTS, point  # lint catches this statically too
    return _PLAN.maybe_fire(point, step)


def active() -> bool:
    return _PLAN is not None


def corrupt_directory(path: str) -> None:
    """Truncate every regular file under ``path`` to one NUL byte — the
    on-disk shape a disk-full or mid-write kill leaves behind.  Used by
    the ``corrupt_snapshot`` fault site (checkpoints.py); destructive by
    design, so it lives here with the drills, not in production code."""
    for dirpath, _dirs, files in os.walk(path):
        for name in files:
            try:
                with open(os.path.join(dirpath, name), 'wb') as f:
                    f.write(b'\0')
            except OSError:
                pass
    logger.warning('FAULT_INJECT: corrupted artifact directory `%s`', path)
