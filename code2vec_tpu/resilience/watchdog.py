"""Hang watchdog: a deadlocked run must fail loud, not burn a
reservation (ROBUSTNESS.md pillar 3).

The two places a healthy trainer can block indefinitely are the input
pipeline (``next`` on the staged batch generator — a wedged prefetch
thread, a hung filesystem) and the per-log-window device sync (a
deadlocked multi-host collective: one process missed a step and the
mesh rendezvous never completes).  The trainer arms the watchdog around
exactly those two waits (``with watchdog.watch('...'):``).

Past the deadline, a daemon monitor thread:

1. dumps ALL Python thread stacks to ``<dump_dir>/watchdog_stacks.txt``
   (``faulthandler`` — safe even when the main thread is wedged inside a
   C call);
2. runs the ``on_expire`` hook (the trainer wires a final telemetry
   flush, so metrics.jsonl records the run's last healthy state);
3. hard-aborts the process — SIGABRT by default, because a wedged
   collective cannot be unwound from Python (no exception reaches a
   thread blocked in C).  Cluster schedulers then see a crashed task
   (restart/reschedule) instead of a silently stalled one.

``abort`` is injectable for in-process tests; the subprocess e2e test
(tests/test_resilience.py) exercises the real SIGABRT path.
"""
from __future__ import annotations

import contextlib
import faulthandler
import os
import signal
import threading
import time
from typing import Callable, Optional


def _default_abort() -> None:
    # SIGABRT, not sys.exit: the hung wait lives in another (often C)
    # frame — only a signal ends the process from the monitor thread.
    os.kill(os.getpid(), signal.SIGABRT)


STACKS_FILE_NAME = 'watchdog_stacks.txt'


class HangWatchdog:
    def __init__(self, deadline_s: float, dump_dir: str, log=None,
                 on_expire: Optional[Callable[[], None]] = None,
                 abort: Optional[Callable[[], None]] = None,
                 poll_s: Optional[float] = None):
        self.deadline_s = float(deadline_s)
        self.dump_dir = dump_dir
        self.log = log or (lambda msg: None)
        self.on_expire = on_expire
        self.abort = abort or _default_abort
        # poll granularity: fine enough to fire within ~10% of the
        # deadline, bounded below for sub-second test deadlines
        self.poll_s = poll_s if poll_s is not None else max(
            0.05, self.deadline_s / 10.0)
        # the training thread arms/disarms while the monitor thread
        # polls; _cond wraps _lock (lock-discipline rule, ANALYSIS.md):
        # graftlint: guard HangWatchdog._armed_at,_label,_stop by _lock|_cond
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._armed_at: Optional[float] = None
        self._label = ''
        self._stop = False
        self._expired = False
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- arming
    def arm(self, label: str) -> None:
        with self._cond:
            self._armed_at = time.monotonic()
            self._label = label
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._monitor, name='hang-watchdog', daemon=True)
                self._thread.start()
            self._cond.notify()
        from code2vec_tpu.telemetry import core
        if core.enabled():
            core.registry().gauge('watchdog/armed').set(1)

    def disarm(self) -> None:
        with self._cond:
            self._armed_at = None
            self._label = ''
        from code2vec_tpu.telemetry import core
        if core.enabled():
            core.registry().gauge('watchdog/armed').set(0)

    @contextlib.contextmanager
    def watch(self, label: str):
        """Arm around one blocking wait; disarms even when the wait
        raises (an input-pipeline error must not later abort an
        otherwise-healthy teardown)."""
        self.arm(label)
        try:
            yield
        finally:
            self.disarm()

    # ------------------------------------------------------------- monitor
    def _monitor(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                armed_at, label = self._armed_at, self._label
                if armed_at is None:
                    self._cond.wait(timeout=self.poll_s)
                    continue
            overdue = time.monotonic() - armed_at - self.deadline_s
            if overdue >= 0:
                self._expire(label)
                return
            time.sleep(min(self.poll_s, -overdue))

    def _expire(self, label: str) -> None:
        self._expired = True
        stacks_path = os.path.join(self.dump_dir, STACKS_FILE_NAME)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(stacks_path, 'w') as f:
                f.write('hang watchdog expired after %.1fs waiting on: '
                        '%s\n\n' % (self.deadline_s, label))
                f.flush()
                faulthandler.dump_traceback(file=f, all_threads=True)
        except OSError:
            stacks_path = '<unwritable: %s>' % stacks_path
        from code2vec_tpu.telemetry import core
        if core.enabled():
            core.registry().counter('watchdog/expired_total').inc()
        self.log('HANG WATCHDOG: `%s` exceeded the %.1fs deadline — '
                 'thread stacks dumped to `%s`; aborting.'
                 % (label, self.deadline_s, stacks_path))
        if self.on_expire is not None:
            try:
                self.on_expire()
            except Exception:
                pass  # the abort below is the priority, not the flush
        self.abort()

    @property
    def expired(self) -> bool:
        return self._expired

    def shutdown(self) -> None:
        """Stop the monitor thread (fit teardown)."""
        with self._cond:
            self._stop = True
            self._armed_at = None
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
