"""Preemption-safe shutdown: turn SIGTERM/SIGINT into a step-boundary
flag the fit loop polls (ROBUSTNESS.md pillar 2).

Spot-VM preemption delivers SIGTERM with a short grace window; Ctrl-C is
SIGINT.  Killing a run mid-step corrupts nothing (jax state is
immutable), but exiting without a save loses everything since the last
``SAVE_EVERY_N_STEPS`` snapshot.  The handler makes the loss at most the
current step: the fit loop checks ``requested`` at each step boundary,
saves one final snapshot (model_api's ``on_preempt``), flushes
telemetry, and returns cleanly.

A SECOND SIGINT raises ``KeyboardInterrupt`` immediately — an operator
hammering Ctrl-C means "now", not "after the snapshot".

Installation is a context manager and is a no-op outside the main thread
(``signal.signal`` raises there — e.g. fits driven from a worker
thread); the previous handlers are restored on exit so nested/serial
trainers never leak a stale flag into the process.
"""
from __future__ import annotations

import signal
import threading
from typing import Dict, Optional


class PreemptionHandler:
    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, log=None):
        self.log = log or (lambda msg: None)
        self._requested = False
        self._signum: Optional[int] = None
        self._sigint_count = 0
        self._previous: Dict[int, object] = {}
        self._installed = False

    # ------------------------------------------------------------- handler
    def _handle(self, signum, frame) -> None:
        if signum == signal.SIGINT:
            self._sigint_count += 1
            if self._sigint_count > 1:
                raise KeyboardInterrupt
        self._requested = True
        self._signum = signum
        self.log('Received %s: finishing the current step, then saving a '
                 'snapshot and exiting cleanly (press Ctrl-C again to '
                 'abort immediately).'
                 % signal.Signals(signum).name)

    @property
    def requested(self) -> bool:
        return self._requested

    @property
    def signal_name(self) -> str:
        return (signal.Signals(self._signum).name
                if self._signum is not None else '')

    # ------------------------------------------------------------ lifecycle
    def install(self) -> 'PreemptionHandler':
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal is main-thread-only: poll-only mode
        for signum in self.SIGNALS:
            try:
                self._previous[signum] = signal.signal(signum, self._handle)
            except (ValueError, OSError):  # exotic embedders
                self._previous.pop(signum, None)
        self._installed = bool(self._previous)
        return self

    def uninstall(self) -> None:
        for signum, previous in self._previous.items():
            try:
                signal.signal(signum, previous)
            except (ValueError, OSError):
                pass
        self._previous.clear()
        self._installed = False

    def __enter__(self) -> 'PreemptionHandler':
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
