"""Training resilience layer (ROBUSTNESS.md).

Four pillars, each wired through the trainer/model lifecycle and each
testable on CPU via deterministic fault injection:

- ``guard``    — divergence guard: non-finite loss window -> rewind to
                 the last snapshot, retry with a bounded budget, abort
                 with diagnostics when the budget burns out.
- ``preempt``  — SIGTERM/SIGINT -> step-boundary flag -> one final
                 snapshot + clean exit (spot-VM preemption loses at most
                 the current step).
- ``watchdog`` — hang monitor armed around the two blocking waits in
                 the hot loop; dumps all thread stacks and hard-aborts
                 past the deadline so a wedged collective fails loud.
- ``faults``   — the deterministic fault-injection harness
                 (``FAULT_INJECT=<point>@<trigger>=<n>,...``) that makes
                 the other three testable; fault points are cataloged in
                 ``faults.FAULT_POINTS`` and linted by
                 ``scripts/check_fault_points.py``.

Everything is stdlib-only at import time (same policy as
``telemetry/``); jax is only touched by the trainer integration.
"""
from __future__ import annotations

from code2vec_tpu.resilience.guard import DivergenceError, DivergenceGuard
from code2vec_tpu.resilience.preempt import PreemptionHandler
from code2vec_tpu.resilience.watchdog import HangWatchdog

__all__ = ['DivergenceError', 'DivergenceGuard', 'PreemptionHandler',
           'HangWatchdog']
