"""Divergence guard: detect a non-finite loss window, rewind to the last
good snapshot, retry with a bounded budget (ROBUSTNESS.md pillar 1).

Detection piggybacks on the hot loop's existing per-log-window
``jax.device_get`` sync (trainer._fit_loop): the windowed losses come to
host there anyway, so the finiteness check costs zero extra host syncs —
``sum(losses)`` is non-finite iff any loss in the window is (NaN
dominates; +inf/-inf sum to NaN or propagate).

On detection the guard:

1. dumps diagnostics — the window's losses, the last batch's label/
   context stats, and a full telemetry registry snapshot — to
   ``<dump_dir>/divergence_step<k>.json`` (the triage artifact the
   runbook starts from);
2. if the rewind budget (``MAX_DIVERGENCE_REWINDS``) is not exhausted,
   restores the newest checkpoint NOT NEWER than the window's FIRST
   non-finite step via the caller-provided ``restore(last_good_step)``
   callback (model_api wires it to ``CheckpointStore.restore_training``
   with that ceiling) — a snapshot saved between the first NaN and its
   detection at the window sync can already hold poisoned params, while
   everything before the first bad loss is clean.  The trainer keeps
   consuming the SAME epoch iterator, so the offending data window is
   skipped, not replayed;
3. otherwise raises ``DivergenceError`` so the run fails loud with the
   dump path in the message.

The guard never rewinds the data: a loss spike caused by one poisonous
window then self-heals (new data, restored params), while a
systematically diverging run burns its budget and aborts.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, List, Optional

import numpy as np


class DivergenceError(RuntimeError):
    """Non-finite loss that the guard could not (or may no longer) rewind
    past."""


def batch_stats(host_batch: Any) -> dict:
    """min/max/shape per array field of a Batch/PackedBatch NamedTuple —
    the 'offending batch' half of the diagnostic dump.  Tolerant of any
    tuple-of-arrays batch type; non-array fields are skipped."""
    stats = {}
    fields = getattr(host_batch, '_asdict', None)
    items = fields().items() if fields else enumerate(host_batch or ())
    for name, value in items:
        if isinstance(value, np.ndarray) and value.size \
                and value.dtype != object:
            stats[str(name)] = {
                'shape': list(value.shape),
                'dtype': str(value.dtype),
                'min': float(value.min()),
                'max': float(value.max()),
            }
    return stats


class DivergenceGuard:
    def __init__(self, max_rewinds: int,
                 restore: Optional[Callable[[int], Optional[Any]]],
                 dump_dir: str, log=None, telemetry=None):
        self.max_rewinds = max_rewinds
        self.restore = restore
        self.dump_dir = dump_dir
        self.log = log or (lambda msg: None)
        self.telemetry = telemetry
        self.rewinds = 0

    def handle(self, batch_num: int, losses: List[float],
               host_batch: Any, step_now: Optional[int] = None) -> Any:
        """Called by the trainer when a log window's losses are
        non-finite.  ``step_now`` is the CURRENT state.step — after an
        earlier rewind it lags the loop's batch counter, and checkpoint
        keys live in step units.  Returns the rewound TrainerState, or
        raises ``DivergenceError``."""
        dump_path = self._dump(batch_num, losses, host_batch)
        self.rewinds += 1
        if self.rewinds > self.max_rewinds:
            raise DivergenceError(
                'Non-finite training loss at batch %d and the rewind '
                'budget (MAX_DIVERGENCE_REWINDS=%d) is exhausted — this '
                'run diverges systematically, not from one bad window. '
                'Diagnostics: %s'
                % (batch_num, self.max_rewinds, dump_path))
        # the window's loss list pinpoints where the divergence began:
        # every step before the FIRST non-finite loss updated params off
        # finite gradients of a finite loss, so snapshots up to there are
        # clean — while a snapshot from the poisoned tail would just
        # diverge again. The ceiling is that first-bad step, in
        # state.step units (checkpoints are keyed by state.step).
        first_bad = next((i for i, x in enumerate(losses)
                          if not np.isfinite(x)), len(losses))
        base = step_now if step_now is not None else batch_num
        last_good_step = max(0, base - len(losses) + first_bad)
        state = (self.restore(last_good_step)
                 if self.restore is not None else None)
        if state is None:
            raise DivergenceError(
                'Non-finite training loss at batch %d and no checkpoint '
                'at or before the last known-finite step %d to rewind to '
                '— enable step-interval snapshots (SAVE_EVERY_N_STEPS) '
                'so the guard has a rewind target. Diagnostics: %s'
                % (batch_num, last_good_step, dump_path))
        from code2vec_tpu.telemetry import core
        if core.enabled():
            # counted only on an ACTUAL restore: aborts above must not
            # read as successful rewinds on a dashboard
            core.registry().counter('resilience/rewinds_total').inc()
        self.log(
            'Divergence guard: non-finite loss window at batch %d; '
            'rewound to checkpoint step %d and skipping the offending '
            'window (rewind %d of %d). Diagnostics: %s'
            % (batch_num, int(state.step), self.rewinds, self.max_rewinds,
               dump_path))
        return state

    def _dump(self, batch_num: int, losses: List[float],
              host_batch: Any) -> str:
        """Best-effort diagnostic JSON; failures to write must never mask
        the divergence handling itself."""
        from code2vec_tpu.telemetry import core
        record = {
            'batch_num': batch_num,
            'time': time.time(),
            'window_losses': [float(x) for x in losses],
            'last_batch': batch_stats(host_batch),
            'telemetry': core.registry().snapshot(),
            'rewinds_so_far': self.rewinds,
        }
        path = os.path.join(self.dump_dir,
                            'divergence_step%d.json' % batch_num)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, 'w') as f:
                json.dump(record, f, indent=1, default=str)
        except OSError as exc:
            self.log('Divergence guard: could not write diagnostics to '
                     '`%s`: %s' % (path, exc))
            return '<unwritable: %s>' % path
        if self.telemetry is not None:
            # a JSONL snapshot of the registry next to the dump: the
            # exporters' view of the run right up to the divergence
            self.telemetry.flush_now(batch_num)
        return path
