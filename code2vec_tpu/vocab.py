"""Vocabularies for tokens / paths / targets.

Disk-format compatible with the reference:

- ``<data>.dict.c2v`` — sequential pickles of token/path/target frequency
  dicts + train example count (reference preprocess.py:12-20,
  vocabularies.py:220-230);
- ``dictionaries.bin`` model sidecar — per-vocab sequential pickles of
  ``word_to_index`` / ``index_to_word`` / ``size`` *without* special words, in
  token → target → path order (reference vocabularies.py:57-97, 211-218).

Device-facing difference from the reference: there are no in-graph lookup
tables (JAX has no string tensors). ``Vocab.lookup_indices`` performs bulk
host-side lookups producing int32 numpy arrays; index→word decoding for
eval/predict also happens on host.
"""
from __future__ import annotations

import hashlib
import os
import pickle
from enum import Enum
from types import SimpleNamespace
from typing import Dict, Iterable, List, NamedTuple, Optional

import numpy as np

from code2vec_tpu import common
from code2vec_tpu.config import Config


class VocabType(Enum):
    Token = 1
    Target = 2
    Path = 3


SpecialWords = SimpleNamespace

# Special-word policies (reference vocabularies.py:22-35).
SPECIAL_WORDS_ONLY_OOV = SimpleNamespace(OOV='<OOV>')
SPECIAL_WORDS_SEPARATE_OOV_PAD = SimpleNamespace(PAD='<PAD>', OOV='<OOV>')
SPECIAL_WORDS_JOINED_OOV_PAD = SimpleNamespace(
    PAD_OR_OOV='<PAD_OR_OOV>', PAD='<PAD_OR_OOV>', OOV='<PAD_OR_OOV>')


class Vocab:
    def __init__(self, vocab_type: VocabType, words: Iterable[str],
                 special_words: Optional[SpecialWords] = None):
        if special_words is None:
            special_words = SimpleNamespace()
        self.vocab_type = vocab_type
        self.special_words = special_words
        self.word_to_index: Dict[str, int] = {}
        self.index_to_word: Dict[int, str] = {}
        for index, word in enumerate(
                common.get_unique_list(special_words.__dict__.values())):
            self.word_to_index[word] = index
            self.index_to_word[index] = word
        for word in words:
            if word in self.word_to_index:
                continue
            index = len(self.word_to_index)
            self.word_to_index[word] = index
            self.index_to_word[index] = word
        self.size = len(self.word_to_index)

    # ------------------------------------------------------------ lookups
    @property
    def oov_index(self) -> int:
        return self.word_to_index[self.special_words.OOV]

    @property
    def pad_index(self) -> int:
        return self.word_to_index[self.special_words.PAD]

    def lookup_index(self, word: str) -> int:
        """word → index with OOV default (the host-side replacement of the
        reference's in-graph StaticHashTable, vocabularies.py:123-127)."""
        return self.word_to_index.get(word, self.oov_index)

    def lookup_indices(self, words: Iterable[str]) -> np.ndarray:
        get = self.word_to_index.get
        oov = self.oov_index
        return np.fromiter((get(w, oov) for w in words), dtype=np.int32)

    def lookup_word(self, index: int) -> str:
        return self.index_to_word.get(int(index), self.special_words.OOV)

    def lookup_words(self, indices: Iterable[int]) -> List[str]:
        get = self.index_to_word.get
        oov = self.special_words.OOV
        return [get(int(i), oov) for i in indices]

    def index_to_word_array(self) -> np.ndarray:
        """Dense object-array of words, index-addressable, for vectorized
        host-side decoding of device top-k outputs."""
        arr = np.empty(self.size, dtype=object)
        for idx, word in self.index_to_word.items():
            arr[idx] = word
        return arr

    # ----------------------------------------------------------- persistence
    def save_to_file(self, file) -> None:
        """Reference-layout save: special words stripped before pickling
        (reference vocabularies.py:57-66)."""
        specials = common.get_unique_list(self.special_words.__dict__.values())
        nr_special = len(specials)
        word_to_index = {w: i for w, i in self.word_to_index.items() if i >= nr_special}
        index_to_word = {i: w for i, w in self.index_to_word.items() if i >= nr_special}
        pickle.dump(word_to_index, file)
        pickle.dump(index_to_word, file)
        pickle.dump(self.size - nr_special, file)

    @classmethod
    def load_from_file(cls, vocab_type: VocabType, file,
                       special_words: SpecialWords) -> 'Vocab':
        """Reference-layout load: special words re-added at the low indices
        (reference vocabularies.py:68-97)."""
        specials = common.get_unique_list(special_words.__dict__.values())
        word_to_index = pickle.load(file)
        index_to_word = pickle.load(file)
        size_wo_specials = pickle.load(file)
        assert len(index_to_word) == len(word_to_index) == size_wo_specials
        if not index_to_word:
            raise ValueError(
                'Stored vocabulary %s is empty (only special words were in '
                'it at save time) — the model was trained on a degenerate '
                'dataset.' % vocab_type)
        min_idx = min(index_to_word.keys())
        if min_idx != len(specials):
            raise ValueError(
                'Stored vocabulary {} has minimum word index {}, expected {} '
                'special words {}. Check config.SEPARATE_OOV_AND_PAD.'.format(
                    vocab_type, min_idx, len(specials), specials))
        vocab = cls(vocab_type, [], special_words)
        vocab.word_to_index = {**word_to_index,
                               **{w: i for i, w in enumerate(specials)}}
        vocab.index_to_word = {**index_to_word,
                               **{i: w for i, w in enumerate(specials)}}
        vocab.size = size_wo_specials + len(specials)
        return vocab

    @classmethod
    def create_from_freq_dict(cls, vocab_type: VocabType,
                              word_to_count: Dict[str, int], max_size: int,
                              special_words: Optional[SpecialWords] = None
                              ) -> 'Vocab':
        """Top-``max_size`` words by count (reference vocabularies.py:99-106;
        ties broken by dict order like the reference's ``sorted``)."""
        words = sorted(word_to_count, key=word_to_count.get, reverse=True)
        return cls(vocab_type, words[:max_size], special_words)


class WordFreqDicts(NamedTuple):
    token_to_count: Dict[str, int]
    path_to_count: Dict[str, int]
    target_to_count: Dict[str, int]


def load_word_freq_dict(path: str) -> WordFreqDicts:
    """Load the ``.dict.c2v`` produced by preprocessing
    (reference vocabularies.py:220-230)."""
    with open(path, 'rb') as file:
        token_to_count = pickle.load(file)
        path_to_count = pickle.load(file)
        target_to_count = pickle.load(file)
    return WordFreqDicts(token_to_count=token_to_count,
                         path_to_count=path_to_count,
                         target_to_count=target_to_count)


class SizeOnlyVocab:
    def __init__(self, size: int):
        self.size = size


class SizeOnlyVocabs:
    """Vocab stand-in carrying only sizes — for benchmarks, the graft entry
    and sharding tests, where no dataset exists."""

    def __init__(self, token: int, path: int, target: int):
        self.token_vocab = SizeOnlyVocab(token)
        self.path_vocab = SizeOnlyVocab(path)
        self.target_vocab = SizeOnlyVocab(target)


class Code2VecVocabs:
    """The {token, path, target} vocabulary triple
    (reference vocabularies.py:151-241)."""

    def __init__(self, config: Config):
        self.config = config
        self.token_vocab: Optional[Vocab] = None
        self.path_vocab: Optional[Vocab] = None
        self.target_vocab: Optional[Vocab] = None
        self._already_saved_in_paths = set()
        self._load_or_create()

    def content_hash(self) -> str:
        """Digest of the three index-ordered word lists — identifies vocab
        *content* (not just sizes) for downstream freshness checks such as
        the token-cache fingerprint.  Stable across the `.dict.c2v` /
        `dictionaries.bin` save-load round trip (same mapping ⇒ same hash),
        unlike a hash of the source file bytes."""
        digest = hashlib.sha256()
        for vocab in (self.token_vocab, self.path_vocab, self.target_vocab):
            lookup = vocab.index_to_word.get
            words = '\x00'.join(lookup(i, '') for i in range(vocab.size))
            digest.update(words.encode('utf-8', 'surrogatepass'))
            digest.update(b'\x01')
        return digest.hexdigest()

    def _load_or_create(self) -> None:
        assert self.config.is_training or self.config.is_loading
        if self.config.is_loading:
            load_path = self.config.get_vocabularies_path_from_model_path(
                self.config.MODEL_LOAD_PATH)
            if not os.path.isfile(load_path):
                raise ValueError(
                    'Model dictionaries file not found: `{}`.'.format(load_path))
            self._load_from_path(load_path)
        else:
            self._create_from_word_freq_dict()

    def _load_from_path(self, load_path: str) -> None:
        self.config.log('Loading model vocabularies from: `%s` ...' % load_path)
        with open(load_path, 'rb') as file:
            # Stored order is token → target → path (reference
            # vocabularies.py:175-184, 211-218).
            self.token_vocab = Vocab.load_from_file(
                VocabType.Token, file, self._special_words_for(VocabType.Token))
            self.target_vocab = Vocab.load_from_file(
                VocabType.Target, file, self._special_words_for(VocabType.Target))
            self.path_vocab = Vocab.load_from_file(
                VocabType.Path, file, self._special_words_for(VocabType.Path))
        self.config.log('Done loading model vocabularies.')
        self._already_saved_in_paths.add(load_path)

    def _create_from_word_freq_dict(self) -> None:
        freq_dicts = load_word_freq_dict(self.config.word_freq_dict_path)
        self.token_vocab = Vocab.create_from_freq_dict(
            VocabType.Token, freq_dicts.token_to_count,
            self.config.MAX_TOKEN_VOCAB_SIZE,
            special_words=self._special_words_for(VocabType.Token))
        self.path_vocab = Vocab.create_from_freq_dict(
            VocabType.Path, freq_dicts.path_to_count,
            self.config.MAX_PATH_VOCAB_SIZE,
            special_words=self._special_words_for(VocabType.Path))
        self.target_vocab = Vocab.create_from_freq_dict(
            VocabType.Target, freq_dicts.target_to_count,
            self.config.MAX_TARGET_VOCAB_SIZE,
            special_words=self._special_words_for(VocabType.Target))
        self.config.log(
            'Created vocabularies: token %d, path %d, target %d' % (
                self.token_vocab.size, self.path_vocab.size,
                self.target_vocab.size))

    def _special_words_for(self, vocab_type: VocabType) -> SpecialWords:
        """Special-word policy (reference vocabularies.py:204-209)."""
        if not self.config.SEPARATE_OOV_AND_PAD:
            return SPECIAL_WORDS_JOINED_OOV_PAD
        if vocab_type == VocabType.Target:
            return SPECIAL_WORDS_ONLY_OOV
        return SPECIAL_WORDS_SEPARATE_OOV_PAD

    def save(self, save_path: str) -> None:
        if save_path in self._already_saved_in_paths:
            return
        with open(save_path, 'wb') as file:
            self.token_vocab.save_to_file(file)
            self.target_vocab.save_to_file(file)
            self.path_vocab.save_to_file(file)
        self._already_saved_in_paths.add(save_path)

    def get(self, vocab_type: VocabType) -> Vocab:
        if vocab_type == VocabType.Token:
            return self.token_vocab
        if vocab_type == VocabType.Target:
            return self.target_vocab
        if vocab_type == VocabType.Path:
            return self.path_vocab
        raise ValueError('`vocab_type` should be a VocabType member.')
