"""Request memoization tier: serve repeated code traffic from cache
before it ever touches the queue or the device (SERVING.md
"Memoization tier").

At fleet scale code traffic is massively duplicated — the same methods
and near-clones arrive thousands of times, and every duplicate pays
full tokenize + queue + device cost.  This module is the cache the
mesh checks at admission, BEFORE ``FrontQueue.admit``: a hit resolves
the caller's future immediately, costing zero device-seconds and no
queue slot (the Ads-serving amortization shape, PAPERS.md).

Two tiers:

- **Exact** (``MEMO_CACHE_BYTES > 0``) — a content-addressed result
  cache keyed by ``request_key``: an order-independent hash over the
  canonicalized path-context bag (``data.reader.canonicalize_contexts``
  truncates each line to ``MAX_CONTEXTS`` in extraction order, then
  sorts the surviving ``(source, path, target)`` triples — duplicates
  kept), scoped per tier and per neighbors ``k``.  Bounded LRU with
  byte accounting registered in the memory ledger (bucket ``memo``,
  ``kind='host'`` — host bytes, deliberately outside the device
  live-array reconciliation).
- **Semantic** (``MEMO_SEMANTIC_EPSILON > 0``; default OFF) — for
  vectors/neighbors traffic: a neighbor query whose code vector lies
  within cosine distance epsilon of a cached query's vector is served
  that cached result (which came from the attached index's lookup on
  the cached code vector).  Every N-th would-be hit is shadow-sampled
  instead: the request runs live and the cached top-1 neighbor is
  compared against the live top-1, exporting
  ``memo/semantic_agreement`` — the canary machinery's top-1 agreement
  metric, reused to measure how aggressive epsilon may be (SERVING.md
  has the agreement-gated rollout runbook).

Correctness contract:

- **Generation-keyed invalidation.**  Every entry records the cache
  generation at insert.  A concluded fleet rollover bumps the
  generation (``ServingMesh.load_params`` → ``bump_generation``) which
  atomically invalidates every pre-swap entry — one O(1) version bump,
  not a per-entry eviction walk; a rolled-BACK canary never calls it,
  so the cache stays warm.  An insert whose request was in flight
  across the swap carries the OLD generation and is refused.
- **Two generation axes.**  Neighbor results depend on the params AND
  the attached index, so their entries also record the INDEX
  generation at insert; a concluded index rollover
  (``ServingMesh.rollover_index`` → ``bump_index_generation``)
  invalidates every index-dependent entry and the whole semantic tier
  while index-independent predict entries survive — the model didn't
  change, so evicting them would only cost warm hits.
- **Delivered-good-only inserts.**  The mesh inserts from a
  done-callback on the caller-visible future, so only results that
  were actually delivered (after oversize re-join, after crash-safe
  redispatch) are cached; errors and cancellations insert nothing.
- **Degraded tiers cannot poison.**  The insert key uses the EFFECTIVE
  (possibly ladder-degraded) tier, the lookup key the REQUESTED tier —
  a degraded 'topk' answer is cached as 'topk', never as 'full'.
- **Caller mutation cannot poison.**  ``insert``/``semantic_insert``
  store a private snapshot (``copy_results``) — the first caller keeps
  the original and may mutate it freely — and every hit is served a
  fresh copy, so no two requesters ever share a row or a numpy array
  with each other or with the cache.
"""
from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.telemetry import catalog
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry import memory as memory_lib
from code2vec_tpu.telemetry.core import Counter, Gauge

__all__ = ['MemoCache', 'copy_results', 'request_key', 'results_nbytes']

#: ledger entry key for the cache's host bytes (bucket ``memo``)
LEDGER_KEY = 'serving_memo'

#: nominal per-entry bookkeeping overhead charged on top of the
#: measured result bytes (key digest + OrderedDict slot + entry object)
ENTRY_OVERHEAD = 128


def request_key(canonical_lines: Sequence[str], tier: str,
                k: Optional[int] = None) -> bytes:
    """Content address of one request: a hash over the canonicalized
    path-context bag, scoped per tier and per neighbors ``k``.
    ``canonical_lines`` MUST already be canonical
    (``canonicalize_contexts``): the per-line sort of the parsed
    ``(source, path, target)`` triples is what makes the hash
    order-independent over each line's context bag.  Line ORDER across
    the request stays part of the identity — results are positional."""
    digest = hashlib.sha256()
    digest.update(('%s|%s' % (tier, k)).encode('utf-8'))
    for line in canonical_lines:
        digest.update(b'\x1e')
        digest.update(line.encode('utf-8', 'surrogatepass'))
    return digest.digest()


def results_nbytes(obj) -> int:
    """Approximate host bytes of a cached result tree
    (``ModelPredictionResults`` / ``NeighborResult`` rows: numpy
    arrays, strings, dicts, tuples).  Metadata and string lengths only
    — never copies, never touches a device."""
    total = 0
    stack = [obj]
    while stack:
        item = stack.pop()
        if isinstance(item, np.ndarray) or isinstance(item, np.generic):
            total += int(item.nbytes)
        elif isinstance(item, (str, bytes)):
            total += len(item)
        elif isinstance(item, dict):
            stack.extend(item.keys())
            stack.extend(item.values())
        elif isinstance(item, (list, tuple)):
            stack.extend(item)
        elif item is None or isinstance(item, (bool, int, float)):
            total += 8
        else:
            total += 64  # opaque object: nominal charge
    return total


def copy_results(obj):
    """Deep-ish copy of a result tree: numpy arrays are copied,
    containers (lists, dicts, tuples — NamedTuple rows like
    ``ModelPredictionResults``/``NeighborResult`` included) are
    rebuilt; immutable leaves (str/bytes/numbers/None) are shared.
    The cache stores a snapshot at insert and serves a fresh copy per
    hit, so a caller mutating what it was handed can never poison what
    every subsequent requester of the same key receives."""
    if isinstance(obj, np.ndarray):
        return obj.copy()
    if isinstance(obj, list):
        return [copy_results(item) for item in obj]
    if isinstance(obj, tuple):
        copied = [copy_results(item) for item in obj]
        if hasattr(obj, '_fields'):  # NamedTuple: rebuild as its type
            return type(obj)(*copied)
        return tuple(copied)
    if isinstance(obj, dict):
        return {key: copy_results(value) for key, value in obj.items()}
    return obj


class _Entry:
    __slots__ = ('results', 'nbytes', 'generation', 'index_generation')

    def __init__(self, results, nbytes: int, generation: int,
                 index_generation: Optional[int] = None):
        self.results = results
        self.nbytes = nbytes
        self.generation = generation
        #: None = index-independent (predict tiers); an int pins the
        #: entry to the index version its result was computed against
        self.index_generation = index_generation


class _SemRow:
    """One cached semantic-tier query: the unit query vector and the
    single-row neighbor result it produced."""

    __slots__ = ('unit', 'result', 'nbytes', 'generation')

    def __init__(self, unit: np.ndarray, result, nbytes: int,
                 generation: int):
        self.unit = unit
        self.result = result
        self.nbytes = nbytes
        self.generation = generation


class MemoCache:
    """The mesh's request memoization cache (exact + semantic tiers).

    Thread contract: ``lookup`` runs on submitter threads, ``insert``
    on decode-completion callbacks, ``bump_generation`` on the rollover
    conclude callback, ``stats`` on monitors — one lock guards all
    cache state (lock-discipline rule, ANALYSIS.md):
    """
    # graftlint: guard MemoCache._entries,_bytes,_generation,_index_generation,_params_step,_sem,_sem_bytes,_sem_rows_total,_sem_serves,_sem_samples,_sem_agree by _lock

    def __init__(self, capacity_bytes: int,
                 semantic_epsilon: float = 0.0,
                 semantic_max_rows: int = 512,
                 semantic_shadow_every: int = 8,
                 params_step: Optional[int] = None,
                 log=None):
        if capacity_bytes <= 0:
            raise ValueError('MemoCache needs capacity_bytes > 0 (got '
                             '%r); a disabled memo tier is no cache, '
                             'not an empty one' % capacity_bytes)
        self.capacity_bytes = int(capacity_bytes)
        self.semantic_epsilon = float(semantic_epsilon)
        self.semantic_max_rows = max(1, int(semantic_max_rows))
        self.semantic_shadow_every = max(2, int(semantic_shadow_every))
        self.log = log if log is not None else (lambda msg: None)
        self._lock = threading.Lock()
        self._entries: 'collections.OrderedDict[bytes, _Entry]' = \
            collections.OrderedDict()
        self._bytes = 0
        self._generation = 0
        self._index_generation = 0
        self._params_step = params_step
        # semantic tier: per-k row store (a neighbor result is only
        # reusable at the same k)
        self._sem: Dict[int, collections.deque] = {}
        self._sem_bytes = 0
        self._sem_rows_total = 0
        self._sem_serves = 0   # candidate hits, for shadow sampling
        self._sem_samples = 0  # shadow comparisons run
        self._sem_agree = 0    # ... that agreed on top-1
        # instruments (catalog family memo/*, OBSERVABILITY.md)
        self.hits_total = Counter('memo/hits_total')
        self.misses_total = Counter('memo/misses_total')
        self.inserts_total = Counter('memo/inserts_total')
        self.evictions_total = Counter('memo/evictions_total')
        self.semantic_hits_total = Counter('memo/semantic_hits_total')
        self.bytes_gauge = Gauge('memo/bytes')
        self.entries_gauge = Gauge('memo/entries')
        self.agreement_gauge = Gauge('memo/semantic_agreement')
        # host-bucket ledger sibling: memo bytes are HOST memory, so
        # kind='host' keeps them out of the device live-array
        # reconciliation while still visible in the taxonomy
        memory_lib.ledger().register('memo', LEDGER_KEY, 0, kind='host')

    # ------------------------------------------------------- exact tier
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def index_generation(self) -> int:
        with self._lock:
            return self._index_generation

    def lookup(self, key: bytes, scenario: Optional[str] = None):
        """A fresh copy of the cached result list for ``key``
        (``copy_results`` — hits never share rows or arrays), or None.
        A hit touches LRU recency; entries from a previous params OR
        index generation never serve (defensive — the bump calls
        already cleared them; an eviction here re-exports the gauges
        and the ledger so they cannot sit stale until the next
        insert).  ``scenario`` additionally mirrors the hit/miss into
        scenario-labeled counter instances, so per-scenario hit-rate
        falls out of the existing ``memo/*`` family (WORKLOADS.md)."""
        stale_total = None
        stale_entries = 0
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and (
                    entry.generation != self._generation
                    or (entry.index_generation is not None
                        and entry.index_generation
                        != self._index_generation)):
                self._entries.pop(key, None)
                self._bytes -= entry.nbytes
                entry = None
                stale_total = self._bytes + self._sem_bytes
                stale_entries = len(self._entries)
            if entry is not None:
                self._entries.move_to_end(key)
        if stale_total is not None:
            self._export(stale_total, stale_entries)
        if entry is None:
            self.misses_total.inc()
            if tele_core.enabled():
                reg = tele_core.registry()
                reg.counter('memo/misses_total').inc()
                if scenario:
                    reg.counter(catalog.labeled(
                        'memo/misses_total', 'scenario',
                        scenario)).inc()
            return None
        self.hits_total.inc()
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('memo/hits_total').inc()
            if scenario:
                reg.counter(catalog.labeled(
                    'memo/hits_total', 'scenario', scenario)).inc()
        # outside the lock: the snapshot stored at insert is never
        # mutated, so the reference read above stays safe to copy
        return copy_results(entry.results)

    def insert(self, key: bytes, results, generation: int,
               index_generation: Optional[int] = None) -> bool:
        """Insert a delivered-good result under the generation(s)
        captured at SUBMIT time — a result in flight across a params
        OR index rollover carries the old generation and is refused
        (stale results can never enter the post-swap cache).
        ``index_generation`` is None for index-independent results
        (predict tiers — they survive an index swap) and the submit
        time ``index_generation`` for neighbor results.  Stores a
        private snapshot (``copy_results``) — the delivering caller
        keeps the original.  Evicts LRU entries to fit; a result
        larger than the whole budget is skipped."""
        nbytes = results_nbytes(results) + len(key) + ENTRY_OVERHEAD
        if nbytes > self.capacity_bytes:
            return False
        results = copy_results(results)
        evicted = 0
        with self._lock:
            if generation != self._generation:
                return False
            if index_generation is not None and \
                    index_generation != self._index_generation:
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._bytes + nbytes > self.capacity_bytes \
                    and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                evicted += 1
            self._entries[key] = _Entry(results, nbytes, generation,
                                        index_generation)
            self._bytes += nbytes
            total = self._bytes + self._sem_bytes
            entries = len(self._entries)
        self.inserts_total.inc()
        if evicted:
            self.evictions_total.inc(evicted)
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('memo/inserts_total').inc()
            if evicted:
                reg.counter('memo/evictions_total').inc(evicted)
        self._export(total, entries)
        return True

    # ---------------------------------------------------- semantic tier
    def semantic_lookup(self, vector, k: int
                        ) -> Optional[Tuple[object, bool]]:
        """Nearest cached query within cosine distance epsilon at this
        ``k``: returns ``(cached_row_result, shadow)`` or None.
        ``shadow=True`` marks a sampled agreement check — the caller
        must run the request LIVE and feed both results to
        ``note_semantic_agreement`` instead of serving the cache.  A
        served row is a fresh copy (``copy_results``); a shadow row is
        the cached reference, read only for the top-1 comparison."""
        if self.semantic_epsilon <= 0:
            return None
        unit = np.asarray(vector, np.float32).reshape(-1)
        norm = float(np.linalg.norm(unit))
        if not np.isfinite(norm) or norm == 0.0:
            return None
        unit = unit / norm
        with self._lock:
            rows = self._sem.get(int(k))
            if not rows:
                return None
            stacked = np.stack([row.unit for row in rows])
            sims = stacked @ unit
            best = int(np.argmax(sims))
            if 1.0 - float(sims[best]) > self.semantic_epsilon:
                return None
            result = rows[best].result
            self._sem_serves += 1
            shadow = (self._sem_serves % self.semantic_shadow_every) == 0
        if not shadow:
            self.semantic_hits_total.inc()
            if tele_core.enabled():
                tele_core.registry().counter(
                    'memo/semantic_hits_total').inc()
            result = copy_results(result)
        return result, shadow

    def semantic_insert(self, vectors, results, k: int,
                        generation: int,
                        index_generation: Optional[int] = None) -> int:
        """Remember each query row's code vector + its neighbor result
        for within-epsilon reuse.  FIFO-bounded at
        ``semantic_max_rows`` across all ``k``.  Semantic rows cache
        INDEX lookups, so a row in flight across an index rollover
        (``index_generation`` captured at submit) is refused exactly
        like a params-rollover straggler.  No-op while the semantic
        tier is OFF (epsilon == 0) — a disabled tier stores nothing
        and costs nothing."""
        if self.semantic_epsilon <= 0:
            return 0
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        inserted = 0
        with self._lock:
            if generation != self._generation:
                return 0
            if index_generation is not None and \
                    index_generation != self._index_generation:
                return 0
            rows = self._sem.setdefault(
                int(k), collections.deque())
            for vec, result in zip(vectors, results):
                norm = float(np.linalg.norm(vec))
                if not np.isfinite(norm) or norm == 0.0:
                    continue
                nbytes = (results_nbytes(result) + int(vec.nbytes)
                          + ENTRY_OVERHEAD)
                # private snapshot: the delivering caller keeps the
                # original row (same isolation contract as insert())
                rows.append(_SemRow(vec / norm, copy_results(result),
                                    nbytes, generation))
                self._sem_bytes += nbytes
                self._sem_rows_total += 1
                inserted += 1
                while self._sem_rows_total > self.semantic_max_rows:
                    self._evict_sem_row_locked()
            total = self._bytes + self._sem_bytes
            entries = len(self._entries)
        if inserted:
            self._export(total, entries)
        return inserted

    def _evict_sem_row_locked(self) -> None:
        """Drop the oldest semantic row across every k (FIFO)."""
        for k, rows in self._sem.items():
            if rows:
                victim = rows.popleft()
                self._sem_bytes -= victim.nbytes
                self._sem_rows_total -= 1
                if not rows:
                    del self._sem[k]
                return

    @staticmethod
    def _top1(row) -> Optional[object]:
        labels = getattr(row, 'labels', None)
        if labels:
            return labels[0]
        indices = getattr(row, 'indices', None)
        if indices is not None and len(indices):
            return int(indices[0])
        return None

    def note_semantic_agreement(self, cached_row, live_row) -> None:
        """One shadow sample concluded: compare the cached top-1
        neighbor against the live top-1 (the canary machinery's
        agreement statistic) and export the running agreement rate —
        the epsilon-aggressiveness dial (SERVING.md runbook)."""
        cached_top = self._top1(cached_row)
        live_top = self._top1(live_row)
        agree = cached_top is not None and cached_top == live_top
        with self._lock:
            self._sem_samples += 1
            self._sem_agree += 1 if agree else 0
            rate = self._sem_agree / self._sem_samples
        self.agreement_gauge.set(rate)
        if tele_core.enabled():
            tele_core.registry().gauge(
                'memo/semantic_agreement').set(rate)

    # ------------------------------------------------------ invalidation
    def bump_generation(self, params_step: Optional[int] = None) -> int:
        """A fleet rollover SWAPPED: one atomic version bump invalidates
        every pre-swap entry (exact and semantic) — not a per-entry
        eviction walk, and not counted as evictions.  A rolled-back
        canary never calls this, so the cache stays warm.  Returns the
        new generation."""
        with self._lock:
            self._generation += 1
            self._params_step = (params_step if params_step is not None
                                 else self._params_step)
            dropped = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self._sem.clear()
            self._sem_bytes = 0
            self._sem_rows_total = 0
            generation = self._generation
        self._export(0, 0)
        self.log('memo: generation -> %d (params step %s); %d cached '
                 'entr%s invalidated atomically'
                 % (generation, params_step, dropped,
                    'y' if dropped == 1 else 'ies'))
        return generation

    def bump_index_generation(self) -> int:
        """An INDEX rollover swapped: invalidate every index-dependent
        entry (neighbor results — ``index_generation`` is not None —
        and the whole semantic tier, which only ever caches index
        lookups) while index-independent predict entries SURVIVE —
        the model didn't change, so their results are still good.
        A rolled-back index canary never calls this.  Returns the new
        index generation."""
        with self._lock:
            self._index_generation += 1
            dropped = 0
            for key in [key for key, entry in self._entries.items()
                        if entry.index_generation is not None]:
                victim = self._entries.pop(key)
                self._bytes -= victim.nbytes
                dropped += 1
            sem_dropped = self._sem_rows_total
            self._sem.clear()
            self._sem_bytes = 0
            self._sem_rows_total = 0
            generation = self._index_generation
            total = self._bytes + self._sem_bytes
            entries = len(self._entries)
        self._export(total, entries)
        self.log('memo: index generation -> %d; %d neighbor entr%s + '
                 '%d semantic row(s) invalidated, %d predict entr%s '
                 'kept'
                 % (generation, dropped,
                    'y' if dropped == 1 else 'ies', sem_dropped,
                    entries, 'y' if entries == 1 else 'ies'))
        return generation

    # --------------------------------------------------------- plumbing
    def _export(self, total_bytes: int, entries: int) -> None:
        self.bytes_gauge.set(total_bytes)
        self.entries_gauge.set(entries)
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.gauge('memo/bytes').set(total_bytes)
            reg.gauge('memo/entries').set(entries)
        # re-register replaces the previous ledger entry: replacing IS
        # the release of the previous size (telemetry/memory.py)
        memory_lib.ledger().register('memo', LEDGER_KEY, total_bytes,
                                     kind='host')

    def stats(self) -> Dict[str, object]:
        with self._lock:
            out = {
                'entries': len(self._entries),
                'bytes': self._bytes + self._sem_bytes,
                'capacity_bytes': self.capacity_bytes,
                'generation': self._generation,
                'index_generation': self._index_generation,
                'params_step': self._params_step,
                'semantic': {
                    'epsilon': self.semantic_epsilon,
                    'rows': self._sem_rows_total,
                    'serves': self._sem_serves,
                    'samples': self._sem_samples,
                    'agreement': (self._sem_agree / self._sem_samples
                                  if self._sem_samples else None),
                },
            }
        hits = self.hits_total.snapshot()
        misses = self.misses_total.snapshot()
        out.update({
            'hits': hits,
            'misses': misses,
            'hit_rate': hits / (hits + misses) if hits + misses else 0.0,
            'inserts': self.inserts_total.snapshot(),
            'evictions': self.evictions_total.snapshot(),
            'semantic_hits': self.semantic_hits_total.snapshot(),
        })
        return out

    def close(self) -> None:
        """Release the ledger entry (idempotent)."""
        memory_lib.ledger().release('memo', LEDGER_KEY)
