"""SLO-driven autoscaler for the serving mesh (SERVING.md "Elastic
fleet").

PR 14 made replica death a non-event and PR 15 made the fleet
observable, but replica COUNT was still fixed at build time: a diurnal
load swing either burns the SLO budget (fleet too small) or wastes
chips (fleet too big).  This module closes the control loop — the
elastic-scaling leg of the Ads-serving stack (PAPERS.md, arXiv
2501.10546): replicas behind one queue, scaled against an explicit
error budget.

**Signals.**  Two scale-UP triggers, evaluated every
``AUTOSCALE_INTERVAL_SECS``:

- the front queue's drain estimate (``FrontQueue.drain_seconds``:
  admitted rows / fleet service rate) exceeds
  ``AUTOSCALE_UP_QUEUE_SECS`` — backlog is outrunning the fleet; a
  stalled fleet with backlog (rate 0) reads as infinite drain;
- the ``SloMonitor`` burn rate (``AUTOSCALE_UP_BURN`` > 0 arms this
  leg): BOTH burn windows of any active SLO above the threshold means
  the error budget is burning — add capacity even if the queue still
  looks shallow (slow replicas, not deep queues, burn p99).

Scale-DOWN is deliberately timid: the fleet must look over-provisioned
CONTINUOUSLY for ``AUTOSCALE_DOWN_IDLE_SECS`` — the drain estimate
with one FEWER replica still under ``AUTOSCALE_DOWN_UTILIZATION x
AUTOSCALE_UP_QUEUE_SECS`` and no SLO burning — before one replica is
drained out.

**Actions.**  Scale-up spawns a local replica (``mesh.add_replica()``
— its own device slice under placement, re-adopted onto the fleet's
current params step) or, with a ``spawn`` hook installed, asks the
ORCHESTRATOR for capacity instead (the hook fires; the new worker
arrives later as an adoption dial-in).  Scale-down is a coordinated
``mesh.retire(rid, reason='autoscale')`` — a drain, NEVER a kill:
in-flight batches deliver, the queue redirects, zero admitted requests
are lost across the transition.  Adopted (orchestrator-owned) and
canarying replicas are never chosen as drain victims.

**Guard rails.**  ``AUTOSCALE_MIN_REPLICAS`` / ``AUTOSCALE_MAX_REPLICAS``
bound the fleet; per-direction cooldowns (``AUTOSCALE_UP_COOLDOWN_SECS``
/ ``AUTOSCALE_DOWN_COOLDOWN_SECS``) stop a single signal from storming;
and a flap guard freezes ALL scaling for ``AUTOSCALE_FLAP_WINDOW_SECS``
once direction reversals in that window reach ``AUTOSCALE_FLAP_LIMIT``
(an oscillating loop is a mis-tuned loop — freezing and counting
``autoscale/flap_freezes_total`` beats thrashing warm ladders).

Every transition is traced (``autoscale.transition``), metered
(``autoscale/*``), and logged with its signal values, so a post-mortem
can replay WHY the fleet changed shape.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter, Gauge


class Autoscaler:
    """The mesh's scaling control loop: one ticker thread reading the
    queue drain estimate + SLO burns, deciding up/down under bounds,
    cooldowns, and the flap guard.  Built and owned by ``ServingMesh``
    when ``AUTOSCALE_MAX_REPLICAS > 0``; ``tick()`` is public so
    drills can step the loop without waiting out the interval."""

    # the ticker mutates, stats()/close() read from other threads
    # (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard Autoscaler._transitions,_last_up,_last_down,_idle_since,_frozen_until,_last_decision,_closed by _lock
    def __init__(self, mesh, config, spawn=None, tracer=None, log=None):
        self.mesh = mesh
        self.min_replicas = max(1, int(config.AUTOSCALE_MIN_REPLICAS))
        self.max_replicas = int(config.AUTOSCALE_MAX_REPLICAS)
        self.interval_s = float(config.AUTOSCALE_INTERVAL_SECS)
        self.up_queue_s = float(config.AUTOSCALE_UP_QUEUE_SECS)
        self.up_burn = float(config.AUTOSCALE_UP_BURN)
        self.down_idle_s = float(config.AUTOSCALE_DOWN_IDLE_SECS)
        self.down_utilization = float(config.AUTOSCALE_DOWN_UTILIZATION)
        self.up_cooldown_s = float(config.AUTOSCALE_UP_COOLDOWN_SECS)
        self.down_cooldown_s = float(
            config.AUTOSCALE_DOWN_COOLDOWN_SECS)
        self.flap_window_s = float(config.AUTOSCALE_FLAP_WINDOW_SECS)
        self.flap_limit = max(1, int(config.AUTOSCALE_FLAP_LIMIT))
        #: orchestrator hook: scale-up REQUESTS capacity instead of
        #: spawning locally (the worker arrives as an adoption dial-in)
        self.spawn = spawn
        self.tracer = tracer
        self.log = log if log is not None else (lambda msg: None)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        #: (t_mono, direction) of recent transitions — the flap guard's
        #: reversal window
        self._transitions: collections.deque = collections.deque()
        self._last_up = -float('inf')
        self._last_down = -float('inf')
        #: when the sustained-low-pressure clock started (None = the
        #: fleet is not currently over-provisioned)
        self._idle_since: Optional[float] = None
        self._frozen_until = 0.0
        self._last_decision = 'hold'
        self.scale_up_total = Counter('autoscale/scale_up_total')
        self.scale_down_total = Counter('autoscale/scale_down_total')
        self.scale_up_failed_total = Counter(
            'autoscale/scale_up_failed_total')
        self.flap_freezes_total = Counter('autoscale/flap_freezes_total')
        self.target_gauge = Gauge('autoscale/replicas_target')

    # ------------------------------------------------------- lifecycle
    def start(self) -> 'Autoscaler':
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name='mesh-autoscale')
            self._thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=180.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # the loop must survive blips
                self.log('autoscale: tick failed: %r' % exc)

    # -------------------------------------------------------- signals
    def _fleet_size(self) -> int:
        """Serving replicas (not retired, not dead) — what a scale
        decision is sized against."""
        mesh = self.mesh
        with mesh._lock:
            return sum(1 for s in mesh._replicas
                       if not s.retired and not s.dead)

    def _burning(self) -> bool:
        """True when any active SLO burns over the scale-up threshold
        on BOTH windows (the multiwindow rule — a blip never scales)."""
        slo = self.mesh._slo
        if slo is None or self.up_burn <= 0:
            return False
        return any(fast > self.up_burn and slow > self.up_burn
                   for fast, slow in slo.burns().values())

    def _over_budget(self) -> bool:
        """Any active SLO burning its budget faster than allowed
        (fast burn > 1): scale-DOWN is vetoed while true."""
        slo = self.mesh._slo
        if slo is None:
            return False
        return any(fast > 1.0 for fast, _slow in slo.burns().values())

    # ------------------------------------------------------- decision
    def tick(self) -> str:
        """One control-loop evaluation; returns the decision
        ('up' | 'down' | 'hold' | 'frozen') for drills to assert on."""
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return 'hold'
            frozen = now < self._frozen_until
        if frozen:
            self._note_decision('frozen')
            return 'frozen'
        n = self._fleet_size()
        drain_s, rows, rate = self.mesh._queue.drain_seconds()
        burning = self._burning()
        if n < self.min_replicas or \
                ((drain_s > self.up_queue_s or burning)
                 and n < self.max_replicas):
            with self._lock:
                in_cooldown = now - self._last_up < self.up_cooldown_s
            if not in_cooldown:
                self._scale_up(n, drain_s, rows, rate, burning, now)
                return 'up'
            self._note_decision('hold')
            return 'hold'
        # ---- scale-down leg: sustained low pressure only ----
        down_ok = False
        if n > self.min_replicas and not burning \
                and not self._over_budget():
            # would the fleet MINUS one replica still be comfortable?
            # per-replica rate = rate/n; with rows and n-1 replicas the
            # projected drain must sit under the utilization floor
            if rows <= 0:
                projected = 0.0
            elif rate <= 0:
                projected = float('inf')
            else:
                projected = rows / (rate * (n - 1) / n)
            down_ok = (projected
                       < self.down_utilization * self.up_queue_s)
        with self._lock:
            if not down_ok:
                self._idle_since = None
                self._last_decision = 'hold'
                return 'hold'
            if self._idle_since is None:
                self._idle_since = now
            sustained = now - self._idle_since >= self.down_idle_s
            in_cooldown = now - self._last_down < self.down_cooldown_s
        if sustained and not in_cooldown:
            if self._scale_down(n, drain_s, rows, rate, now):
                return 'down'
        self._note_decision('hold')
        return 'hold'

    def _note_decision(self, decision: str) -> None:
        with self._lock:
            self._last_decision = decision

    def _note_transition(self, direction: str, now: float) -> bool:
        """Record a transition; returns False (and freezes) when the
        reversal count inside the flap window hits the limit."""
        with self._lock:
            horizon = now - self.flap_window_s
            while self._transitions and \
                    self._transitions[0][0] < horizon:
                self._transitions.popleft()
            reversals = sum(
                1 for (_, a), (_, b) in zip(self._transitions,
                                            list(self._transitions)[1:])
                if a != b)
            if self._transitions and \
                    self._transitions[-1][1] != direction:
                reversals += 1
            if reversals >= self.flap_limit:
                self._frozen_until = now + self.flap_window_s
                self._last_decision = 'frozen'
                frozen_for = self.flap_window_s
            else:
                self._transitions.append((now, direction))
                return True
        self.flap_freezes_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter(
                'autoscale/flap_freezes_total').inc()
        self.log('autoscale: FLAP GUARD — %d direction reversals '
                 'inside %.0fs (limit %d); freezing all scaling for '
                 '%.0fs (re-tune the thresholds instead of thrashing '
                 'warm ladders)'
                 % (self.flap_limit, self.flap_window_s,
                    self.flap_limit, frozen_for))
        return False

    # -------------------------------------------------------- actions
    def _trace(self, direction: str, attrs: Dict[str, object]):
        if self.tracer is None:
            return None
        attrs = dict(attrs)
        attrs['direction'] = direction
        return self.tracer.begin('autoscale.transition', attrs=attrs)

    def _set_target(self, target: int) -> None:
        self.target_gauge.set(target)
        if tele_core.enabled():
            tele_core.registry().gauge(
                'autoscale/replicas_target').set(target)

    def _scale_up(self, n: int, drain_s: float, rows: int,
                  rate: float, burning: bool, now: float) -> None:
        if not self._note_transition('up', now):
            return
        with self._lock:
            self._last_up = now
            self._idle_since = None
            self._last_decision = 'up'
        reason = ('slo_burn' if burning and drain_s <= self.up_queue_s
                  else 'queue_drain' if not burning
                  else 'queue_drain+slo_burn')
        self._set_target(n + 1)
        trace = self._trace('up', {
            'from': n, 'to': n + 1, 'reason': reason,
            'drain_s': None if drain_s == float('inf') else drain_s,
            'queue_rows': rows, 'fleet_rows_per_s': rate})
        self.log('autoscale: scaling UP %d -> %d (%s: drain %.1fs vs '
                 '%.1fs, %d rows queued, fleet %.0f rows/s%s)'
                 % (n, n + 1, reason,
                    drain_s if drain_s != float('inf') else -1.0,
                    self.up_queue_s, rows, rate,
                    ', slo burning' if burning else ''))
        try:
            if self.spawn is not None:
                # orchestrator-owned capacity: the hook requests a
                # worker; it arrives later as an adoption dial-in
                self.spawn(self.mesh)
            else:
                self.mesh.add_replica()
        except BaseException as exc:
            self.scale_up_failed_total.inc()
            if tele_core.enabled():
                tele_core.registry().counter(
                    'autoscale/scale_up_failed_total').inc()
            self.log('autoscale: scale-up FAILED (%r); cooldown '
                     'applies before the next attempt' % exc)
            if trace is not None:
                trace.finish(status='error', reason=repr(exc))
            return
        self.scale_up_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter(
                'autoscale/scale_up_total').inc()
        if trace is not None:
            trace.finish(status='ok')

    def _pick_victim(self) -> Optional[str]:
        """NEWEST eligible replica drains first (LIFO keeps the
        longest-warm ladders serving).  Never an adopted worker (the
        orchestrator owns its lifecycle), never the canary (a rollover
        in flight must conclude), never an already-dead slot (the
        supervisor owns it)."""
        mesh = self.mesh
        with mesh._lock:
            for slot in reversed(mesh._replicas):
                if slot.retired or slot.dead or slot.canarying \
                        or slot.adopted:
                    continue
                return slot.rid
        return None

    def _scale_down(self, n: int, drain_s: float, rows: int,
                    rate: float, now: float) -> bool:
        victim = self._pick_victim()
        if victim is None:
            self._note_decision('hold')
            return False
        if not self._note_transition('down', now):
            return False
        with self._lock:
            self._last_down = now
            self._idle_since = None
            self._last_decision = 'down'
        self._set_target(n - 1)
        trace = self._trace('down', {
            'from': n, 'to': n - 1, 'replica': victim,
            'drain_s': None if drain_s == float('inf') else drain_s,
            'queue_rows': rows, 'fleet_rows_per_s': rate})
        self.log('autoscale: scaling DOWN %d -> %d — draining replica '
                 '%s (drain %.2fs, %d rows queued, fleet %.0f rows/s; '
                 'sustained %.0fs under the utilization floor)'
                 % (n, n - 1, victim,
                    drain_s if drain_s != float('inf') else -1.0,
                    rows, rate, self.down_idle_s))
        try:
            # a DRAIN, never a kill: in-flight batches deliver and the
            # queue redirects before the engine closes
            self.mesh.retire(victim, reason='autoscale')
        except BaseException as exc:
            self.log('autoscale: scale-down of %s failed (%r)'
                     % (victim, exc))
            if trace is not None:
                trace.finish(status='error', reason=repr(exc))
            return False
        self.scale_down_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter(
                'autoscale/scale_down_total').inc()
        if trace is not None:
            trace.finish(status='ok')
        return True

    # --------------------------------------------------------- report
    def stats(self) -> Dict[str, object]:
        with self._lock:
            frozen_for = max(0.0, self._frozen_until - time.monotonic())
            decision = self._last_decision
            transitions = len(self._transitions)
        return {
            'min_replicas': self.min_replicas,
            'max_replicas': self.max_replicas,
            'scale_up_total': self.scale_up_total.snapshot(),
            'scale_down_total': self.scale_down_total.snapshot(),
            'scale_up_failed_total':
                self.scale_up_failed_total.snapshot(),
            'flap_freezes_total': self.flap_freezes_total.snapshot(),
            'replicas_target': self.target_gauge.snapshot(),
            'last_decision': decision,
            'recent_transitions': transitions,
            'frozen_for_s': frozen_for,
        }
