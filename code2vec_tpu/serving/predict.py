"""Interactive prediction REPL (reference interactive_predict.py:28-57).

Loop: user edits ``Input.java`` → extractor subprocess produces path
contexts → model predicts → print top-k names with probabilities,
per-context attention (paths un-hashed for display), and optionally the
code vector.
"""
from __future__ import annotations

from typing import Optional

from code2vec_tpu import common
from code2vec_tpu.config import Config
from code2vec_tpu.serving.extractor_bridge import Extractor

SHOW_TOP_CONTEXTS = 10           # reference interactive_predict.py:6
DEFAULT_INPUT_FILENAME = 'Input.java'
EXIT_KEYWORDS = ['exit', 'quit', 'q']


class InteractivePredictor:
    def __init__(self, config: Config, model,
                 extractor: Optional[Extractor] = None,
                 input_filename: str = DEFAULT_INPUT_FILENAME):
        self.config = config
        self.model = model
        self.path_extractor = extractor or Extractor(config)
        self.input_filename = input_filename

    def predict(self) -> None:
        print('Starting interactive prediction...')
        while True:
            print('Modify the file: "%s" and press any key when ready, or '
                  '"q" / "quit" / "exit" to exit' % self.input_filename)
            user_input = input()
            if user_input.lower() in EXIT_KEYWORDS:
                print('Exiting...')
                return
            try:
                predict_lines, hash_to_string_dict = \
                    self.path_extractor.extract_paths(self.input_filename)
            except ValueError as e:
                print(e)
                continue
            raw_results = self.model.predict(predict_lines)
            results = common.parse_prediction_results(
                raw_results, hash_to_string_dict,
                self.model.vocabs.target_vocab.special_words.OOV,
                topk=SHOW_TOP_CONTEXTS)
            for raw_result, method_result in zip(raw_results, results):
                print('Original name:\t' + method_result.original_name)
                for name_prob_pair in method_result.predictions:
                    print('\t(%f) predicted: %s' % (
                        name_prob_pair['probability'],
                        name_prob_pair['name']))
                print('Attention:')
                for attention in method_result.attention_paths:
                    print('%f\tcontext: %s,%s,%s' % (
                        attention['score'], attention['token1'],
                        attention['path'], attention['token2']))
                if self.config.EXPORT_CODE_VECTORS:
                    print('Code vector:')
                    print(' '.join(map(str, raw_result.code_vector)))
