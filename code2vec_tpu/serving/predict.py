"""Interactive prediction shell.

A thin presentation layer over the batch ``model.predict`` API: read a
source file, run the extractor bridge, predict every method in one batched
call, and render a per-method report.  The display tokens ("Original
name:", "Attention:", the per-context lines) follow the reference REPL's
output contract (reference interactive_predict.py:47-57) — that format is
user-facing spec; the code below is this framework's own decomposition:
``predict_file`` (extract → batch predict → parse) and
``render_method_report`` (pure result → text) are reusable outside the
REPL loop, e.g. for one-shot CLI prediction or tests.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

from code2vec_tpu import common
from code2vec_tpu.config import Config
from code2vec_tpu.serving.extractor_bridge import Extractor, infer_language

SHOW_TOP_CONTEXTS = 10           # reference interactive_predict.py:6
# single source of truth: Config.PREDICT_INPUT_PATH (the --input-file
# flag's default) — duplicating the literal here let the two drift
DEFAULT_INPUT_FILENAME = Config.PREDICT_INPUT_PATH
QUIT_WORDS = frozenset({'exit', 'quit', 'q'})


def resolve_input_path(input_filename: str) -> str:
    """Language inference at the predict entry point.

    ``PREDICT_INPUT_PATH`` defaults to ``Input.java``, which used to
    leave the C# leg reachable only via ``--input-file Input.cs``.
    Inference from the file EXTENSION is now the default: when the
    configured file does not exist but exactly one sibling with a
    known source extension does (``Input.cs`` next to a missing
    ``Input.java``), predict over that sibling — the extractor bridge
    then selects the matching frontend from the extension
    (``infer_language``).  An existing file, or an ambiguous set of
    siblings, is returned unchanged."""
    if os.path.exists(input_filename):
        return input_filename
    stem = os.path.splitext(input_filename)[0]
    candidates = [stem + ext for ext in ('.java', '.cs')
                  if infer_language(stem + ext) is not None
                  and os.path.exists(stem + ext)]
    if len(candidates) == 1:
        return candidates[0]
    return input_filename


def predict_contexts(model, context_lines, path_unhash,
                     topk: int = SHOW_TOP_CONTEXTS) -> List[Tuple[object, object]]:
    """Predict every method in one batched ``model.predict`` call.

    Returns ``[(method_result, raw_result), ...]`` — the parsed
    presentation view paired with the raw backend output (which carries
    the code vector).
    """
    raw_results = model.predict(context_lines)
    parsed = common.parse_prediction_results(
        raw_results, path_unhash,
        model.vocabs.target_vocab.special_words.OOV, topk=topk)
    return list(zip(parsed, raw_results))


def predict_file(model, extractor: Extractor, source_path: str,
                 topk: int = SHOW_TOP_CONTEXTS) -> List[Tuple[object, object]]:
    """Extract path contexts from ``source_path``, then ``predict_contexts``.
    Raises ``ValueError`` if the extractor finds no parseable method."""
    context_lines, path_unhash = extractor.extract_paths(source_path)
    return predict_contexts(model, context_lines, path_unhash, topk)


def render_method_report(method_result,
                         code_vector: Optional[Sequence[float]] = None) -> str:
    """Pure text rendering of one method's prediction (display contract:
    reference interactive_predict.py:47-57)."""
    lines = [f'Original name:\t{method_result.original_name}']
    lines.extend(
        f"\t({candidate['probability']:f}) predicted: {candidate['name']}"
        for candidate in method_result.predictions)
    lines.append('Attention:')
    lines.extend(
        f"{ctx['score']:f}\tcontext: {ctx['token1']},{ctx['path']},{ctx['token2']}"
        for ctx in method_result.attention_paths)
    if code_vector is not None:
        lines.append('Code vector:')
        lines.append(' '.join(map(str, code_vector)))
    return '\n'.join(lines)


class InteractivePredictor:
    """REPL driving ``predict_file`` over a user-edited input file."""

    def __init__(self, config: Config, model,
                 extractor: Optional[Extractor] = None,
                 input_filename: Optional[str] = None):
        self.config = config
        self.model = model
        self.path_extractor = extractor or Extractor(config)
        # config is the single source of truth for the input file
        # (--input-file -> Config.PREDICT_INPUT_PATH); the kwarg remains
        # an explicit override for tests and embedding callers
        self.input_filename = (input_filename
                               or config.PREDICT_INPUT_PATH)

    def predict(self) -> None:
        print('Starting interactive prediction...')
        prompt = (f'Modify the file: "{self.input_filename}" and press any '
                  'key when ready, or "q" / "quit" / "exit" to exit')
        while True:
            print(prompt)
            if input().lower() in QUIT_WORDS:
                print('Exiting...')
                return
            try:
                # Only extraction errors are user-recoverable (bad input
                # file); model-side failures must surface, not re-prompt.
                # Re-resolve EVERY turn: creating Input.cs mid-session
                # switches the REPL to the C# frontend without a flag.
                context_lines, path_unhash = \
                    self.path_extractor.extract_paths(
                        resolve_input_path(self.input_filename))
            except ValueError as e:
                print(e)
                continue
            reports = predict_contexts(self.model, context_lines,
                                       path_unhash)
            for method_result, raw_result in reports:
                vector = (raw_result.code_vector
                          if self.config.EXPORT_CODE_VECTORS else None)
                print(render_method_report(method_result, vector))
