"""High-throughput serving engine: dynamic micro-batching over a fixed
ladder of warm, pre-compiled programs.

The naive serving shape — one ``model.predict`` per request — compiles a
fresh XLA program for every distinct request size, batches nothing
across requests, and computes + transfers attention weights and code
vectors even when the caller wants neither. TPU serving systems instead
coalesce ragged concurrent requests into a small set of pre-compiled
bucketed shapes and keep the device queue full (Ragged Paged Attention,
arxiv 2604.15464; Google's ads-serving infrastructure, arxiv 2501.10546
— PAPERS.md). This module is that shape for code2vec:

- **Bucket ladder.** Batch buckets (``Config.SERVING_BATCH_BUCKETS``,
  each rounded up to a multiple of the mesh data axis) × packed-capacity
  rungs (``data/packed.py::capacity_ladder`` — the eager-compile
  counterpart of training's StickyPacker bucketing) × output tiers
  (``training/trainer.py::PREDICT_TIERS``). ``warmup()`` compiles every
  program in the ladder at load, so steady-state serving never compiles
  (compile-counter-asserted in tests/test_serving_bench.py).
- **Dynamic micro-batcher.** ``submit()`` tokenizes on the caller thread
  and enqueues; a dispatcher thread coalesces concurrent requests under
  a max-latency deadline (``SERVING_MAX_DELAY_MS``) into the smallest
  covering batch bucket, packs them over the compact wire format
  (data/packed.py — the 0.24x bytes win applies directly to the h2d
  serving path), and dispatches asynchronously, so the device queue
  stays full while the NEXT batch coalesces.
- **Decode offload.** Host-side decode (device fetch, top-k word lookup,
  attention parsing) runs on a worker pool (``SERVING_DECODE_WORKERS``),
  so device dispatch never waits on Python.

Resilient under overload and across model refreshes (ROBUSTNESS.md
serving pillar; SERVING.md "Overload & rollover runbook"):

- **Admission control.** The front queue is bounded
  (``SERVING_QUEUE_BOUND`` rows); submissions past it — or whose SLO
  deadline (``SERVING_DEADLINE_MS`` / per-``submit`` ``deadline_ms=``)
  the queue's drain estimate already exceeds — are shed with a typed
  ``EngineOverloaded`` at admission. Queued requests whose deadline
  passes are expired with ``DeadlineExceeded`` instead of dispatching
  dead work, and a degradation ladder downgrades output tier
  (full → attention → topk) while the queue runs hot.
- **Canaried zero-downtime rollover.** ``load_params(step|path|pytree)``
  loads candidate params alongside the serving set, shadow-scores live
  micro-batches against both (same shapes and shardings — the warm
  ladder is reused, zero new compiles), and atomically swaps when top-1
  agreement clears ``SERVING_CANARY_AGREEMENT``, else rolls back.
  ``follow_checkpoints`` polls the store and rolls newer steps in.

Instrumented with standalone telemetry instruments (``stats()``) that
mirror into the process-global registry when telemetry is enabled
(``serving/*`` in telemetry/catalog.py; OBSERVABILITY.md).

One engine is one replica: a fleet of them serves behind ONE shared
front queue as a ``ServingMesh`` (serving/mesh.py; SERVING.md "Serving
mesh") — the engine then runs in **external-dispatch mode**
(``external_dispatch=True``): no private queue or dispatcher thread,
the mesh's replica puller feeds ``dispatch_external()`` directly, and
every registry mirror below is replica-labeled
(``serving/...{replica=rN}``) so coexisting replicas never collide in
the process-global registry.

Typical use::

    engine = model.serving_engine()          # warm-compiles the ladder
    future = engine.submit(context_lines)    # -> Future[list[results]]
    results = engine.predict(context_lines)  # sync convenience
    engine.close()                           # or `with model.serving_engine() as engine:`

SERVING.md has the architecture, the latency/throughput model, and the
runbook.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.data.reader import (Batch, EstimatorAction,
                                      PathContextReader,
                                      canonicalize_contexts)
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving.errors import (DeadlineExceeded, EngineClosed,
                                         EngineOverloaded)
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry import memory as memory_lib
from code2vec_tpu.telemetry import tracing as tracing_lib
from code2vec_tpu.telemetry.core import Counter, Gauge, Timer
from code2vec_tpu.training.trainer import PREDICT_TIERS

#: overload degradation ladder: tier served at each level (missing keys
#: keep the requested tier). Level 1 sheds the attention decode of
#: 'full'; level 2 serves bare top-k only. 'vectors' is never remapped —
#: its callers need the vectors, not a cheaper answer.
_DEGRADE_LADDER = {
    1: {'full': 'attention'},
    2: {'full': 'topk', 'attention': 'topk'},
}
#: queue-fill fractions (of the admission bound): enter level 2 / enter
#: level 1 / drop back to 0. The wide exit gap is the hysteresis that
#: makes the ladder respond to SUSTAINED overload instead of flapping
#: on every burst.
_OVERLOAD_ENTER_2 = 0.75
_OVERLOAD_ENTER_1 = 0.50
_OVERLOAD_EXIT = 0.25

#: sliding window the drain-estimate throughput aggregates over, and the
#: minimum span it must cover before it overrides the sojourn seed — a
#: burst of near-simultaneous completions spans microseconds and carries
#: no throughput signal
_SERVICE_WINDOW_S = 2.0
_SERVICE_MIN_SPAN_S = 0.05

#: Serializes the ASYNC device enqueue of predict programs across
#: coexisting engines (mesh replicas share one device mesh in-process).
#: Two threads interleaving their per-device enqueues of
#: collective-bearing SPMD programs can cross the programs' rendezvous
#: and deadlock the backend (observed: two replicas' AllGathers wedged
#: on the 8-device CPU test mesh).  Holding the lock only for the
#: enqueue imposes a consistent per-device program order; the
#: executions themselves still pipeline (per-device streams run them
#: in order), so the serialized section is microseconds, not step time.
_DISPATCH_ENQUEUE_LOCK = threading.Lock()


# --------------------------------------------------------------- ladder
def batch_ladder(buckets: Sequence[int], data_axis: int) -> Tuple[int, ...]:
    """Sorted, deduplicated batch buckets, each rounded UP to a multiple
    of the mesh data axis so every bucket shards evenly."""
    if data_axis < 1:
        raise ValueError('data_axis must be >= 1, got %d' % data_axis)
    out = set()
    for bucket in buckets:
        bucket = int(bucket)
        if bucket < 1:
            raise ValueError('batch buckets must be >= 1, got %d' % bucket)
        out.add(-(-bucket // data_axis) * data_axis)
    return tuple(sorted(out))


def pick_bucket(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket covering ``n`` rows, or None when ``n`` exceeds
    the ladder (callers split, or fall back to ad-hoc padding)."""
    for bucket in ladder:
        if bucket >= n:
            return bucket
    return None


def attention_per_context(source_strings, path_strings, target_strings,
                          attention_weights) -> Dict[Tuple[str, str, str],
                                                     float]:
    """Per-context attention dict, skipping padding contexts (reference
    model_base.py:115-129). Single definition — model_api and the engine
    decode both use it."""
    out: Dict[Tuple[str, str, str], float] = {}
    for source, path, target, weight in zip(
            source_strings, path_strings, target_strings,
            attention_weights):
        if not source and not path and not target:
            continue  # padding context
        out[(str(source), str(path), str(target))] = float(weight)
    return out


def decode_results(fetched: Dict[str, np.ndarray], batch: Batch,
                   n_rows: int, decode_table: np.ndarray) -> list:
    """Host numpy outputs + the (string-bearing) plane batch -> one
    ``ModelPredictionResults`` per row. Only the keys the tier produced
    are present in ``fetched``; absent tiers decode to empty/None."""
    # lazy: model_api imports this module (circularity-free direction)
    from code2vec_tpu.model_api import ModelPredictionResults
    topk_indices = fetched.get('topk_indices')
    topk_scores = fetched.get('topk_scores')
    attention = fetched.get('attention')
    code_vectors = fetched.get('code_vectors')
    results = []
    for r in range(n_rows):
        attn = {}
        if attention is not None and batch.source_strings is not None:
            attn = attention_per_context(
                batch.source_strings[r], batch.path_strings[r],
                batch.target_strings[r], attention[r])
        results.append(ModelPredictionResults(
            original_name=(str(batch.label_strings[r])
                           if batch.label_strings is not None else ''),
            topk_predicted_words=(list(decode_table[topk_indices[r]])
                                  if topk_indices is not None else []),
            topk_predicted_words_scores=(topk_scores[r]
                                         if topk_scores is not None
                                         else None),
            attention_per_context=attn,
            code_vector=(code_vectors[r]
                         if code_vectors is not None else None)))
    return results


# ------------------------------------------------------------- requests
def _resolve(future: Future, results: list) -> None:
    """set_result tolerating an already-done future: a caller may
    cancel() (these futures are never marked running, so cancel always
    succeeds) — its own result is then dropped, but delivery to the
    OTHER requests coalesced into the same micro-batch must proceed."""
    if not future.done():
        try:
            future.set_result(results)
        except Exception:
            pass  # lost the race to a concurrent cancel


class _Aggregate:
    """Joins the chunk results of one oversize request back into its
    caller-visible future, preserving row order."""

    # decode workers race on the chunk slots (lock-discipline rule,
    # ANALYSIS.md):
    # graftlint: guard _Aggregate.parts,left by lock
    def __init__(self, future: Future, n_chunks: int, trace=None):
        self.future = future
        self.parts: List[Optional[list]] = [None] * n_chunks
        self.left = n_chunks
        self.trace = trace  # the chunks' SHARED trace; finished at join
        self.lock = threading.Lock()

    def deliver(self, idx: int, results: list) -> None:
        with self.lock:
            self.parts[idx] = results
            self.left -= 1
            # snapshot under the lock: the last-chunk decider must not
            # re-read `parts` barehanded after releasing it
            done = list(self.parts) if self.left == 0 else None
        if done is not None:
            merged: list = []
            for part in done:
                merged.extend(part)
            _resolve(self.future, merged)
            if self.trace is not None:
                self.trace.event('serving.join',
                                 attrs={'chunks': len(done),
                                        'rows': len(merged)})
                self.trace.finish(status='ok')

    def fail(self, exc: BaseException) -> None:
        # first failure wins; set_exception on a done future raises
        if not self.future.done():
            try:
                self.future.set_exception(exc)
            except Exception:
                pass


class _Request:
    """One queue entry: a tokenized chunk of <= max-bucket rows."""

    __slots__ = ('batch', 'rows', 'tier', 'future', 'aggregate',
                 'chunk_idx', 't_enqueue', 't_deadline', 'trace',
                 'span_parent', 'queue_span', 'redispatched', 'exclude')

    def __init__(self, batch: Batch, tier: str,
                 future: Optional[Future] = None,
                 aggregate: Optional[_Aggregate] = None,
                 chunk_idx: int = 0,
                 deadline_s: Optional[float] = None,
                 trace=None, span_parent=None):
        self.batch = batch
        self.rows = int(batch.label.shape[0])
        self.tier = tier
        self.future = future
        self.aggregate = aggregate
        self.chunk_idx = chunk_idx
        # this request's trace (chunks of one oversize submit SHARE it;
        # span_parent is then the chunk span, phases nest under it)
        self.trace = trace
        self.span_parent = span_parent
        self.queue_span = None  # open serving.queue_wait span
        self.t_enqueue = time.perf_counter()
        # absolute expiry instant on the t_enqueue clock; None = no SLO
        self.t_deadline = (self.t_enqueue + deadline_s
                           if deadline_s else None)
        # crash-safe redispatch state (serving/mesh.py): a batch that
        # dies with its worker re-admits its members ONCE at the queue
        # front, excluding the dead replica incarnation
        self.redispatched = False
        self.exclude = None

    def deliver(self, results: list) -> None:
        if self.aggregate is not None:
            self.aggregate.deliver(self.chunk_idx, results)
        else:
            _resolve(self.future, results)

    def finish_trace(self) -> None:
        """Trace bookkeeping after a successful deliver: chunks close
        their chunk span (the shared trace finishes at the aggregate
        join); single requests finish their trace here."""
        if self.trace is None:
            return
        if self.aggregate is not None:
            if self.span_parent is not None:
                self.trace.end(self.span_parent)
        else:
            self.trace.finish(status='ok')

    def fail(self, exc: BaseException) -> None:
        if self.trace is not None:
            # every typed-failed future still gets a terminal span with
            # its reason — no trace is ever truncated by shutdown
            if isinstance(exc, EngineClosed):
                self.trace.event('serving.closed',
                                 parent=self.span_parent,
                                 attrs={'reason': str(exc)})
                self.trace.finish(status='closed')
            elif isinstance(exc, DeadlineExceeded):
                self.trace.event('serving.expired',
                                 parent=self.span_parent,
                                 attrs={'reason': str(exc)})
                self.trace.finish(status='expired')
            else:
                self.trace.finish(status='error', reason=repr(exc))
        if self.aggregate is not None:
            self.aggregate.fail(exc)
        elif not self.future.done():
            self.future.set_exception(exc)


def bound_rejects(admitted: int, rows: int,
                  bound: Optional[int]) -> bool:
    """The admission bound's pile-up rule, shared by the engine's
    ``_admit`` and the mesh's ``FrontQueue.admit``: the bound rejects
    request PILE-UP, not request size — a single request larger than
    the whole bound (the oversize-splitting contract) is admitted
    alone on an idle queue; its own size then bounds the queue, and
    everything behind it sheds until it drains."""
    if bound is None or admitted + rows <= bound:
        return False
    return rows <= bound or admitted > 0


def overload_tier(admitted: int, rows: int, bound: Optional[int],
                  level: int, tier: str,
                  warm_tiers: Sequence[str]) -> Tuple[int, str]:
    """One hysteresis step of the degradation ladder, shared by engine
    and mesh admission: returns ``(new_level, effective_tier)``.  The
    wide enter/exit gap makes the ladder respond to SUSTAINED overload
    instead of flapping on bursts; a downgrade never lands on a cold
    program (``warm_tiers``)."""
    if bound is not None:
        fill = (admitted + rows) / bound
        if fill >= _OVERLOAD_ENTER_2:
            level = 2
        elif fill >= _OVERLOAD_ENTER_1:
            level = max(level, 1)
        elif fill < _OVERLOAD_EXIT:
            level = 0
    effective = _DEGRADE_LADDER.get(level, {}).get(tier, tier)
    if effective != tier and effective not in warm_tiers:
        effective = tier
    return level, effective


def note_service_window(window: collections.deque, window_rows: int,
                        rate: float, rows: int,
                        oldest_enqueue: Optional[float]
                        ) -> Tuple[int, float]:
    """One completion's update of the sliding served-rows/s window —
    the drain-estimate math shared by ``ServingEngine._note_service``
    (one replica) and ``ServingMesh`` (every replica's completions →
    the fleet rate).  Mutates ``window`` in place and returns the new
    ``(window_rows, rate)``; the caller holds its own lock.  See
    ``_note_service`` for why throughput-over-a-window (not sojourn,
    not inter-completion gaps) is the right estimator."""
    now = time.perf_counter()
    window.append((now, rows))
    window_rows += rows
    horizon = now - _SERVICE_WINDOW_S
    while len(window) > 1 and window[0][0] < horizon:
        _t, evicted = window.popleft()
        window_rows -= evicted
    anchor_t, anchor_rows = window[0]
    span = now - anchor_t
    if span >= _SERVICE_MIN_SPAN_S:
        # the anchor's own rows completed AT the span's start — they
        # represent work done before it and are excluded
        rate = (window_rows - anchor_rows) / span
    elif rate <= 0 and oldest_enqueue is not None:
        # seed from batch sojourn until the window spans a measurable
        # interval — biased low, so a shed too many, never a deadline
        # promised and missed
        rate = rows / max(1e-6, now - oldest_enqueue)
    return window_rows, rate


def tokenize_and_chunk(reader: PathContextReader,
                       lines: Sequence[str], tier: str, future: Future,
                       deadline_s: Optional[float], trace,
                       t_tokenize0: float,
                       max_bucket: int) -> List['_Request']:
    """Caller-thread tokenize + oversize chunking, shared by
    ``ServingEngine.submit`` and ``ServingMesh.submit``: one request at
    or under the top bucket stays whole; larger ones split into
    ``_Request`` chunks re-joined in order through an ``_Aggregate``
    (chunk spans nest each chunk's phases under the shared trace)."""
    batch = reader.process_input_rows(lines)
    if trace is not None:
        trace.span_at('serving.tokenize', t_tokenize0,
                      time.perf_counter())
    n = int(batch.label.shape[0])
    if n <= max_bucket:
        return [_Request(batch, tier, future=future,
                         deadline_s=deadline_s, trace=trace)]
    n_chunks = -(-n // max_bucket)
    aggregate = _Aggregate(future, n_chunks, trace=trace)
    requests = []
    for i in range(n_chunks):
        chunk = PathContextReader._take_rows(
            batch, slice(i * max_bucket, (i + 1) * max_bucket))
        chunk_span = None
        if trace is not None:
            chunk_span = trace.span(
                'serving.chunk',
                attrs={'chunk': i, 'of': n_chunks,
                       'rows': int(chunk.label.shape[0])})
        requests.append(_Request(
            chunk, tier, aggregate=aggregate, chunk_idx=i,
            deadline_s=deadline_s, trace=trace,
            span_parent=chunk_span))
    return requests


class _Rollover:
    """One in-flight canaried param rollover: the candidate params plus
    the canary tallies. All fields are mutated under the engine's
    ``_cond`` lock (the dispatcher reads it, decode workers tally into
    it, ``load_params``/``close`` create and clear it)."""

    __slots__ = ('params', 'step', 'handle', 'target_batches',
                 'min_agreement', 't_armed', 'batches', 'rows',
                 'agree_rows', 'primary_fetch_s', 'shadow_fetch_s')

    def __init__(self, params, step: Optional[int], handle: Future,
                 target_batches: int, min_agreement: float):
        self.params = params
        self.step = step
        self.handle = handle
        self.target_batches = target_batches
        self.min_agreement = min_agreement
        self.t_armed = time.perf_counter()
        self.batches = 0
        self.rows = 0
        self.agree_rows = 0
        self.primary_fetch_s = 0.0
        self.shadow_fetch_s = 0.0

    def report(self, swapped: bool, reason: str) -> Dict[str, object]:
        rows = max(1, self.rows)
        return {
            'swapped': swapped,
            'reason': reason,
            'step': self.step,
            'agreement': (self.agree_rows / rows if self.rows else None),
            'batches': self.batches,
            'rows': self.rows,
            'primary_fetch_ms': 1e3 * self.primary_fetch_s
            / max(1, self.batches),
            'shadow_fetch_ms': 1e3 * self.shadow_fetch_s
            / max(1, self.batches),
        }


# --------------------------------------------------------------- engine
class ServingEngine:
    """Warm-compiled, micro-batching inference engine over a model's
    trainer + params. Build via ``Code2VecModel.serving_engine()``.

    Thread-safe: ``submit`` may be called from any number of threads;
    one dispatcher thread coalesces, ``decode_workers`` threads decode.
    """

    def __init__(self, config, trainer, params, vocabs,
                 decode_table: np.ndarray,
                 tiers: Optional[Sequence[str]] = None,
                 max_delay_ms: Optional[float] = None,
                 decode_workers: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 queue_bound: Optional[int] = None,
                 canary_batches: Optional[int] = None,
                 canary_agreement: Optional[float] = None,
                 param_source=None,
                 params_step: Optional[int] = None,
                 tracer: Optional[tracing_lib.Tracer] = None,
                 tracing_sample_rate: Optional[float] = None,
                 replica_id: Optional[str] = None,
                 external_dispatch: bool = False,
                 on_batch_done=None,
                 log=None):
        self.config = config
        # mesh-replica identity (serving/mesh.py): labels this engine's
        # registry mirrors so N coexisting replicas never double-count a
        # counter or overwrite each other's gauges, and stamps the
        # dispatch spans for per-replica latency attribution
        self.replica_id = replica_id
        # external-dispatch mode: the engine compiles/dispatches/decodes
        # but owns NO queue — a ServingMesh dispatcher feeds it through
        # dispatch_external(); submit()/follow_checkpoints() are the
        # mesh's job and refuse here
        self._external = bool(external_dispatch)
        # completion hook (mesh replica table): called from the decode
        # worker as (engine, rows, taken, ok) once a dispatched batch
        # delivered (or typed-failed) — drives the mesh's in-flight
        # window, fleet drain estimate, and dispatch-share gauges
        self._on_batch_done = on_batch_done
        self.trainer = trainer
        self.params = params
        self.decode_table = decode_table
        self.log = log if log is not None else (lambda msg: None)
        self.mesh = trainer.mesh
        self.data_axis = self.mesh.shape[mesh_lib.DATA_AXIS]
        # predict semantics: rows are never filtered; strings ride along
        # for the attention tiers' decode
        self.reader = PathContextReader(vocabs, config,
                                        EstimatorAction.Predict)
        import jax
        if jax.process_count() > 1:
            # per-host request queues cannot agree on batch contents
            # without a coordination layer; multi-host serving runs one
            # engine per host replica over that host's own mesh instead
            raise NotImplementedError(
                'ServingEngine is single-host only (runs on %d '
                'processes); serve one engine replica per host.'
                % jax.process_count())
        self.wire = config.wire_format_for(jax.process_count())
        self.buckets = batch_ladder(config.serving_batch_buckets,
                                    self.data_axis)
        # capacity rungs per bucket: a bucket's per-shard stream can hold
        # at most (bucket / data_axis) * MAX_CONTEXTS retained slots
        self.capacities: Dict[int, Tuple[int, ...]] = {
            bucket: packed_lib.capacity_ladder(
                (bucket // self.data_axis) * config.MAX_CONTEXTS)
            for bucket in self.buckets}
        tiers = tuple(tiers if tiers is not None
                      else config.serving_warm_tiers)
        for tier in tiers:
            if tier not in PREDICT_TIERS:
                raise ValueError('unknown tier %r; expected a subset of %s'
                                 % (tier, PREDICT_TIERS))
        self.tiers = tiers
        self.max_delay_s = (max_delay_ms if max_delay_ms is not None
                            else config.SERVING_MAX_DELAY_MS) / 1e3
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else config.SERVING_DEADLINE_MS)
        # default SLO deadline in seconds; None = no deadline
        self.deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None
        bound = (queue_bound if queue_bound is not None
                 else config.SERVING_QUEUE_BOUND)
        # admission bound in queued rows; None = unbounded (-1), auto (0)
        # = a few in-flight fills of the top bucket
        self.queue_bound: Optional[int] = (
            None if bound < 0 else
            8 * self.buckets[-1] if bound == 0 else bound)
        self.canary_batches = (canary_batches
                               if canary_batches is not None
                               else config.SERVING_CANARY_BATCHES)
        self.canary_agreement = (canary_agreement
                                 if canary_agreement is not None
                                 else config.SERVING_CANARY_AGREEMENT)
        self.canary_timeout_s = config.SERVING_CANARY_TIMEOUT_SECS
        # resolves load_params(step|path) refs and newest_step() polls;
        # None on engines built from bare params (load_params then only
        # accepts a params pytree)
        self._param_source = param_source
        workers = (decode_workers if decode_workers is not None
                   else config.SERVING_DECODE_WORKERS)
        # the registry mirror for every emission site below: the plain
        # process-global registry for a standalone engine, a replica-
        # labeled view of it (serving/x_total{replica=rN}) for a mesh
        # replica — telemetry/catalog.py "Instance labels"
        if replica_id is not None:
            self._mirror = tele_core.ScopedRegistry(
                tele_core.registry(), 'replica', replica_id)
        else:
            self._mirror = tele_core.registry()
        # standalone instruments: stats()/benchmarks read them without
        # enabling the process-global telemetry layer; emission sites
        # below mirror into the registry when telemetry is on
        self.latency = Timer('serving/latency_ms')
        self.dispatch_timer = Timer('serving/dispatch_ms')
        self.decode_timer = Timer('serving/decode_ms')
        self.requests_total = Counter('serving/requests_total')
        self.batches_total = Counter('serving/batches_total')
        self.queue_depth = Gauge('serving/queue_depth')
        self.fill_rate = Gauge('serving/batch_fill_rate')
        self.shed_total = Counter('serving/shed_total')
        self.expired_total = Counter('serving/expired_total')
        self.degraded_total = Counter('serving/degraded_total')
        self.overload_level_gauge = Gauge('serving/overload_level')
        self.rollover_total = Counter('serving/rollover_total')
        self.rollover_rollbacks_total = Counter(
            'serving/rollover_rollbacks_total')
        self.rollover_agreement = Gauge('serving/rollover_agreement')
        self.last_dispatch: Optional[Dict[str, int]] = None
        # submitters, the dispatcher, decode workers, and close() share
        # the queue / rollover / overload state; _cond wraps _lock, so
        # holding either alias guards the fields (lock-discipline rule,
        # ANALYSIS.md):
        # graftlint: guard ServingEngine._queues,_pending_rows,_reserved_rows,_closed,_drain,params,_rollover,_params_step,_overload_level,_peak_rows,_service_rows_per_s,_service_window,_service_window_rows by _lock|_cond
        # graftlint: guard ServingEngine._warm by _warm_lock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, collections.deque] = {
            tier: collections.deque() for tier in PREDICT_TIERS}
        self._pending_rows: Dict[str, int] = {t: 0 for t in PREDICT_TIERS}
        # rows admitted but not yet enqueued (tokenizing on the caller
        # thread): counted against the bound so concurrent submitters
        # cannot overshoot it between admission and enqueue
        self._reserved_rows = 0
        self._closed = False
        self._drain = False  # close(drain=True) serves the queue first
        self._rollover: Optional[_Rollover] = None
        # the retained step the serving params came from (wired by
        # model.serving_engine() from the restored checkpoint): the
        # follow-checkpoints baseline, so the first poll doesn't pay a
        # full restore + canary to re-roll the already-serving step
        self._params_step: Optional[int] = params_step
        self._overload_level = 0
        self._peak_rows = 0
        # served rows/sec over a sliding window of decode completions —
        # the drain estimate admission compares against deadlines
        self._service_rows_per_s = 0.0
        self._service_window: collections.deque = collections.deque()
        self._service_window_rows = 0  # sum of rows in _service_window
        self._warm = False
        self._index = None  # attach_index() arms submit_neighbors
        self._warm_lock = threading.Lock()
        # per-request tracing (telemetry/tracing.py; OBSERVABILITY.md
        # "Per-request serving traces"): head-sampled span log + the
        # always-on flight recorder. rate 0 = no tracer, and every
        # instrumented site below reduces to one `is not None` check.
        rate = (tracing_sample_rate if tracing_sample_rate is not None
                else config.tracing_sample_rate)
        # an INJECTED tracer belongs to its injector (a mesh shares one
        # across every replica; a bench reads it after the run): only a
        # tracer this engine constructed is closed by engine.close()
        self._owns_tracer = tracer is None
        if tracer is not None:
            self._tracer: Optional[tracing_lib.Tracer] = tracer
        elif rate > 0:
            out_dir = None
            if getattr(config, 'TELEMETRY_DIR', None) or \
                    config.is_saving or config.is_loading:
                # only write span logs where the run already keeps
                # artifacts; with no such directory the tracer runs
                # memory-only (ring works, nothing lands in the CWD)
                from code2vec_tpu.telemetry.stepwatch import telemetry_dir
                out_dir = telemetry_dir(config)
            self._tracer = tracing_lib.Tracer(
                out_dir, sample_rate=rate,
                slow_ms=config.TRACING_SLOW_MS,
                flight_traces=config.TRACING_FLIGHT_TRACES,
                # a worker-mode mesh replica shares the parent's
                # telemetry dir: namespace its flight dumps
                # (flight_<event>_r<N>.jsonl) so two processes never
                # clobber one postmortem file
                instance=replica_id,
                log=self.log)
        else:
            self._tracer = None
        # device-memory ledger (telemetry/memory.py): the engine's
        # initial params are the MODEL's allocation (registered by its
        # owner — trainer init or checkpoint restore), so the engine
        # registers nothing at construction; it owns only the sets IT
        # brings in — a rollover candidate while armed, and the
        # swapped-in serving set afterwards (fixed per-engine keys, so
        # replacement is release).  The abstract param bytes feed the
        # load_params budget precheck.
        self._mem_prefix = 'engine:%x' % id(self)
        self._params_nbytes = memory_lib.tree_nbytes(
            trainer.backend.param_shapes())
        self._follow_thread: Optional[threading.Thread] = None
        self._follow_stop = threading.Event()
        self._decode_pool = ThreadPoolExecutor(
            max_workers=max(1, workers),
            thread_name_prefix='serving-decode'
            + ('' if replica_id is None else '-%s' % replica_id))
        if self._external:
            # a mesh replica owns no queue: the mesh's replica puller
            # is the dispatcher (serving/mesh.py)
            self._dispatcher: Optional[threading.Thread] = None
        else:
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name='serving-dispatch')
            self._dispatcher.start()

    # ---------------------------------------------------------- warmup
    def _warm_batches(self, bucket: int):
        """Device-shaped zero batches for one bucket — every wire shape
        the dispatcher can produce for it (programs key on shapes, not
        values; all-PAD rows are valid model input)."""
        contexts = self.config.MAX_CONTEXTS
        if self.wire == 'packed':
            token_pad = self.trainer._token_pad
            path_pad = self.trainer._path_pad
            for cap in self.capacities[bucket]:
                ctx = np.empty((self.data_axis, cap, 3), np.int32)
                ctx[..., 0] = token_pad
                ctx[..., 1] = path_pad
                ctx[..., 2] = token_pad
                yield (ctx, np.zeros((bucket,), np.int32),
                       np.zeros((bucket,), np.int32),
                       np.zeros((bucket,), np.float32))
        else:
            yield (np.zeros((bucket, contexts), np.int32),
                   np.zeros((bucket, contexts), np.int32),
                   np.zeros((bucket, contexts), np.int32),
                   np.zeros((bucket, contexts), np.float32),
                   np.zeros((bucket,), np.int32),
                   np.zeros((bucket,), np.float32))

    def warmup(self) -> 'ServingEngine':
        """Eagerly compile every (bucket x capacity x tier) program in
        the ladder, so steady-state ``submit`` traffic never compiles.
        Idempotent; auto-invoked by the first ``submit`` if skipped."""
        import jax
        with self._warm_lock:
            if self._warm:
                return self
            with self._lock:
                params = self.params
            t0 = time.perf_counter()
            programs = 0
            # executables-bucket accounting (telemetry/memory.py): one
            # AOT memory_analysis per ladder program — an extra compile
            # each, so only for runs that opted into the telemetry
            # LAYER (config), not merely a registry something else
            # enabled in-process (steady state stays compile-free
            # either way; the guards count POST-warmup)
            measure_memory = (tele_core.enabled()
                              and getattr(self.config, 'TELEMETRY',
                                          False))
            ledger = memory_lib.ledger()
            try:
                for bucket in self.buckets:
                    for host_arrays in self._warm_batches(bucket):
                        arrays = mesh_lib.shard_batch(
                            host_arrays, self.mesh,
                            self.config.SHARD_CONTEXTS, direct=True)
                        capacity = (int(host_arrays[0].shape[1])
                                    if self.wire == 'packed' else 0)
                        for tier in self.tiers:
                            out = self.trainer.predict_step_placed(
                                params, arrays, tier=tier)
                            jax.block_until_ready(out)
                            programs += 1
                            if not measure_memory:
                                continue
                            info = self.trainer.predict_program_memory(
                                params, arrays, tier=tier)
                            if info is not None:
                                # keyed and owned by the TRAINER, not
                                # this engine: the compiled programs
                                # live in the trainer's jit caches, so
                                # they survive engine.close() and are
                                # shared by every engine over the same
                                # trainer — trainer-keyed entries match
                                # that lifetime exactly and re-warm as
                                # a replace, never a double-count
                                ledger.register(
                                    'executables',
                                    '%s/%s/b%d/c%d'
                                    % (self.trainer._mem_key, tier,
                                       bucket, capacity),
                                    (info['generated_code_bytes']
                                     + info['temp_bytes']),
                                    kind='executable',
                                    owner=self.trainer,
                                    attrs={'tier': tier,
                                           'bucket': bucket,
                                           'capacity': capacity,
                                           **info})
            except Exception as exc:
                # OOM forensics at the warm-compile boundary: a ladder
                # that does not fit dumps attribution before dying
                ledger.note_oom(exc, 'serving.warmup')
                raise
            warm_s = time.perf_counter() - t0
            if tele_core.enabled():
                reg = self._mirror
                reg.gauge('serving/warmup_s').set(warm_s)
                reg.gauge('serving/programs_warm').set(programs)
            self.log('serving: warmed %d programs (buckets %s x tiers %s, '
                     '%s wire) in %.1fs'
                     % (programs, list(self.buckets), list(self.tiers),
                        self.wire, warm_s))
            self._warm = True
        return self

    # ------------------------------------------------------- admission
    def _shed_locked(self, rows: int, why: str) -> None:
        """Reject one submission at admission (typed, nothing enqueued)."""
        self.shed_total.inc()
        if tele_core.enabled():
            self._mirror.counter('serving/shed_total').inc()
        raise EngineOverloaded(
            'request shed at admission (%s): %d rows, %d rows queued, '
            'bound %s — retry against another replica or back off'
            % (why, rows, self._admitted_rows_locked(),
               self.queue_bound))

    def _admitted_rows_locked(self) -> int:
        return sum(self._pending_rows.values()) + self._reserved_rows

    def _admit(self, rows: int, tier: str,
               deadline_s: Optional[float]) -> str:
        """Admission control for one submission: bound check, drain
        estimate vs deadline, degradation ladder. Reserves ``rows``
        against the bound (released on enqueue or failure) and returns
        the EFFECTIVE tier to serve."""
        with self._cond:
            if self._closed:
                raise EngineClosed('ServingEngine is closed')
            if faults.maybe_fire('reject_all'):
                self._shed_locked(rows, 'reject_all drill')
            admitted = self._admitted_rows_locked()
            bound = self.queue_bound
            if bound_rejects(admitted, rows, bound):
                self._shed_locked(rows, 'queue bound')
            if deadline_s is not None and self._service_rows_per_s > 0:
                drain_s = (admitted + rows) / self._service_rows_per_s
                if drain_s > deadline_s:
                    self._shed_locked(
                        rows, 'drain estimate %.0fms > deadline %.0fms'
                        % (1e3 * drain_s, 1e3 * deadline_s))
            level, effective = overload_tier(
                admitted, rows, bound, self._overload_level, tier,
                self.tiers)
            if level != self._overload_level:
                self._overload_level = level
                self.overload_level_gauge.set(level)
                if tele_core.enabled():
                    self._mirror.gauge(
                        'serving/overload_level').set(level)
            if effective != tier:
                self.degraded_total.inc()
                if tele_core.enabled():
                    self._mirror.counter(
                        'serving/degraded_total').inc()
            self._reserved_rows += rows
            self._peak_rows = max(self._peak_rows,
                                  self._admitted_rows_locked())
            if tele_core.enabled():
                self._mirror.gauge(
                    'serving/queue_peak_rows').set(self._peak_rows)
        return effective

    # ---------------------------------------------------------- submit
    def submit(self, context_lines: Sequence[str],
               tier: str = 'topk',
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one prediction request (raw extractor/``.c2v`` context
        lines, like ``model.predict``). Returns a Future resolving to
        one ``ModelPredictionResults`` per line, in order. Requests
        larger than the top batch bucket are split transparently.

        ``deadline_ms`` overrides the engine's default SLO deadline for
        this request (0 = none): past it the request is shed at
        admission or expired in the queue with a typed error, never
        dispatched."""
        if self._external:
            raise RuntimeError(
                'this engine is a mesh replica (external dispatch); '
                'submit through its ServingMesh (serving/mesh.py)')
        if tier not in self.tiers:
            raise ValueError('tier %r is not warmed on this engine '
                             '(tiers=%s)' % (tier, list(self.tiers)))
        # graftlint: disable=lock-discipline -- benign racy fast-fail: a close() racing past this read is re-checked under _cond before enqueue below
        if self._closed:
            raise EngineClosed('ServingEngine is closed')
        # ONE definition of request identity across engine + mesh +
        # memo key (data/reader.py canonicalize_contexts; idempotent at
        # fixed MAX_CONTEXTS — process_input_rows applies it too, so the
        # tokenizer and any caller-side key derivation can never
        # disagree).  MAX_CONTEXTS must reach the FIRST call: it
        # truncates in extraction order before the canonical sort.
        lines = canonicalize_contexts(context_lines,
                                      self.config.MAX_CONTEXTS)
        future: Future = Future()
        if not lines:
            future.set_result([])
            return future
        # graftlint: disable=lock-discipline -- benign racy read: warmup() is idempotent and re-checks _warm under _warm_lock
        if not self._warm:
            self.warmup()
        n = len(lines)
        if deadline_ms is None:
            deadline_s = self.deadline_s
        else:
            deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None
        self.requests_total.inc()
        if tele_core.enabled():
            self._mirror.counter('serving/requests_total').inc()
        trace = None
        if self._tracer is not None:
            trace = self._tracer.begin(
                'serving.request',
                attrs={'tier': tier, 'rows': n,
                       'deadline_ms': (1e3 * deadline_s
                                       if deadline_s else None)})
        requested_tier = tier
        t_admit0 = time.perf_counter()
        try:
            tier = self._admit(n, tier, deadline_s)  # raises typed on shed
        except EngineOverloaded as exc:
            if trace is not None:
                trace.event('serving.shed', attrs={'reason': str(exc)})
                trace.finish(status='shed')
                self._tracer.note_shed()
            raise
        except EngineClosed as exc:
            if trace is not None:
                trace.event('serving.closed', attrs={'reason': str(exc)})
                trace.finish(status='closed')
            raise
        t_admit1 = time.perf_counter()
        if trace is not None:
            trace.span_at('serving.admission', t_admit0, t_admit1)
            if tier != requested_tier:
                trace.event('serving.degraded',
                            attrs={'requested': requested_tier,
                                   'effective': tier})
        try:
            requests = tokenize_and_chunk(
                self.reader, lines, tier, future, deadline_s, trace,
                t_admit1, self.buckets[-1])
        except BaseException as exc:
            with self._cond:
                self._reserved_rows -= n
            if trace is not None:
                trace.finish(status='error', reason=repr(exc))
            raise
        with self._cond:
            self._reserved_rows -= n
            if self._closed:
                closed_exc = EngineClosed('ServingEngine is closed')
            else:
                closed_exc = None
                for request in requests:
                    if request.trace is not None:
                        request.queue_span = request.trace.span(
                            'serving.queue_wait',
                            parent=request.span_parent,
                            t0=request.t_enqueue)
                    self._queues[tier].append(request)
                    self._pending_rows[tier] += request.rows
                self._set_queue_depth_locked()
                self._cond.notify_all()
        if closed_exc is not None:
            if trace is not None:
                trace.event('serving.closed',
                            attrs={'reason': str(closed_exc)})
                trace.finish(status='closed')
            raise closed_exc
        return future

    def predict(self, context_lines: Sequence[str], tier: str = 'topk',
                timeout: Optional[float] = None) -> list:
        """Synchronous ``submit().result()`` convenience."""
        return self.submit(context_lines, tier).result(timeout)

    # -------------------------------------------------------- neighbors
    def attach_index(self, index) -> 'ServingEngine':
        """Arm ``submit_neighbors`` with a k-NN index over the corpus
        (code2vec_tpu/index/, INDEX.md). The engine must have the
        'vectors' tier warmed — neighbor queries ride it through the
        same micro-batching dispatcher as every other tier.

        Memory accounting (telemetry/memory.py): the attach path's
        HBM budget gate lives in the index constructors — ``ExactIndex``
        / ``IVFIndex`` predict their device footprint and fail typed
        (``MemoryBudgetExceeded``) BEFORE placing anything, so by the
        time an index reaches here it is both resident and
        ledger-registered under the ``index`` bucket."""
        if 'vectors' not in self.tiers:
            raise ValueError(
                "submit_neighbors needs the 'vectors' tier warmed on "
                'this engine (tiers=%s); build it with '
                "tiers=('vectors', ...) or SERVING_WARM_TIERS."
                % list(self.tiers))
        self._index = index
        return self

    def submit_neighbors(self, context_or_vectors, k: Optional[int] = None
                         ) -> Future:
        """One warm round-trip from code to its nearest corpus methods:
        raw context lines (like ``submit``) ride the micro-batched
        'vectors' tier, and the resulting code vectors feed the attached
        index; an ``(n, D)`` vector array skips the predict leg. Returns
        a Future of one ``NeighborResult`` per input row, in order."""
        index = self._index
        if index is None:
            raise RuntimeError('no index attached — call '
                               'attach_index(load_index(...)) first '
                               '(code2vec_tpu/index/service.py)')
        k = k if k is not None else self.config.INDEX_NEIGHBORS_K
        from code2vec_tpu.index.service import neighbors_from_search
        outer: Future = Future()
        if isinstance(context_or_vectors, np.ndarray):
            vectors = np.atleast_2d(context_or_vectors)

            def lookup():
                try:
                    values, indices = index.search(vectors, k)
                    _resolve(outer, neighbors_from_search(
                        values, indices, index.labels))
                except BaseException as exc:
                    if not outer.done():
                        outer.set_exception(exc)
            self._decode_pool.submit(lookup)
            return outer
        inner = self.submit(context_or_vectors, tier='vectors')

        def chain(done: Future) -> None:
            # runs on the decode worker that resolved `inner` — the
            # index search stays off the dispatcher thread
            try:
                results = done.result()
                if not results:
                    _resolve(outer, [])
                    return
                vectors = np.stack([r.code_vector for r in results])
                values, indices = index.search(vectors, k)
                _resolve(outer, neighbors_from_search(
                    values, indices, index.labels))
            except BaseException as exc:
                if not outer.done():
                    outer.set_exception(exc)
        inner.add_done_callback(chain)
        return outer

    def predict_neighbors(self, context_or_vectors,
                          k: Optional[int] = None,
                          timeout: Optional[float] = None) -> list:
        """Synchronous ``submit_neighbors().result()`` convenience."""
        return self.submit_neighbors(context_or_vectors, k).result(timeout)

    # -------------------------------------------------------- rollover
    def _check_rollover_clear_locked(self) -> None:
        if self._closed:
            raise EngineClosed('ServingEngine is closed')
        if self._rollover is not None:
            raise RuntimeError(
                'a rollover is already in flight (step %s); await '
                'its handle first' % self._rollover.step)

    def load_params(self, source, canary_batches: Optional[int] = None,
                    min_agreement: Optional[float] = None) -> Future:
        """Canaried zero-downtime checkpoint rollover (SERVING.md).

        ``source`` is a retained checkpoint step (int), a model path
        (str) — both resolved through the engine's param source (wired
        by ``model.serving_engine()``) — or a placed params pytree.
        Candidate params must match the serving set's shapes and
        shardings, so every shadow dispatch reuses the warm ladder:
        a live rollover compiles NOTHING.

        With ``canary_batches > 0`` (default ``SERVING_CANARY_BATCHES``)
        the next live micro-batches are shadow-scored against both param
        sets; the swap happens atomically once top-1 agreement over the
        canaried rows clears ``min_agreement`` (default
        ``SERVING_CANARY_AGREEMENT``), else the candidate is dropped.
        ``canary_batches == 0`` swaps immediately.

        Returns a Future resolving to the rollover report dict
        (``{'swapped': bool, 'agreement': ..., ...}``); the canary needs
        live traffic to conclude. Fails with ``EngineClosed`` if the
        engine closes first."""
        handle: Future = Future()
        step: Optional[int] = None
        with self._cond:
            # advisory fast-fail before the checkpoint restore below —
            # a full Orbax read + device placement is too expensive to
            # spend on a call doomed by a closed engine or an in-flight
            # rollover; the locked re-check after the load stays
            # authoritative (the engine can close during the restore)
            self._check_rollover_clear_locked()
        if isinstance(source, (int, str)) and not isinstance(source, bool):
            if self._param_source is None:
                raise RuntimeError(
                    'load_params(%r): this engine has no param source — '
                    'build it via model.serving_engine(), or pass a '
                    'params pytree' % (source,))
            if isinstance(source, int):
                step = source
            # budget precheck (telemetry/memory.py): the candidate is a
            # FULL second param set resident next to the serving one for
            # the whole canary — predict its footprint from the abstract
            # shapes and fail typed BEFORE the restore allocates
            memory_lib.ledger().check_budget(
                self._params_nbytes,
                'serving rollover candidate (%r)' % (source,))
            params = self._param_source.load(source)
        else:
            params = source
        n_canary = (canary_batches if canary_batches is not None
                    else self.canary_batches)
        floor = (min_agreement if min_agreement is not None
                 else self.canary_agreement)
        if n_canary > 0 and all(t == 'vectors' for t in self.tiers):
            # the canary compares top-1 predictions, which the vectors
            # tier does not produce: an armed canary would never
            # conclude and wedge every later rollover
            raise RuntimeError(
                'canaried rollover needs a top-k-producing tier warmed '
                '(tiers=%s are vectors-only); pass canary_batches=0 to '
                'swap without a canary, or warm a topk tier'
                % list(self.tiers))
        report = None
        if n_canary > 0:
            # the armed canary's SECOND param-set copy is visible in the
            # ledger for exactly as long as it is resident. Registered
            # BEFORE arming: every path that can retire the candidate
            # (a decode worker concluding the canary, the dispatch-time
            # timeout, close) only becomes reachable once the entry
            # exists, so none of them can race a late register into a
            # phantom entry.
            memory_lib.ledger().register(
                'params', self._mem_prefix + '/candidate', params,
                owner=self, attrs={'step': step, 'state': 'candidate'})
        try:
            with self._cond:
                self._check_rollover_clear_locked()
                rollover = _Rollover(params, step, handle, n_canary,
                                     floor)
                if n_canary <= 0:
                    self.params = params
                    if step is not None:
                        self._params_step = step
                    report = rollover.report(True, 'no canary configured')
                else:
                    self._rollover = rollover
        except BaseException:
            if n_canary > 0:
                self._mem_drop_candidate()  # arming refused: not resident
            raise
        if report is not None:
            self._mem_swap_in(params, step)
            self._count_rollover(True, None)
            self.log('serving: params swapped without canary (step %s)'
                     % step)
            handle.set_result(report)
        else:
            self.log('serving: rollover armed (step %s): canarying %d '
                     'live batches, agreement floor %.2f'
                     % (step, n_canary, floor))
        return handle

    def adopt_params(self, params, step: Optional[int] = None) -> None:
        """Atomically swap the serving params with NO canary and NO
        ledger registration: the fleet-swap leg of a coordinated mesh
        rollover (serving/mesh.py), where the canary replica already
        validated this exact param set against live traffic and the
        mesh owns the ONE ledger entry for the shared arrays —
        per-replica re-registration of the same pytree would N-count
        it. Refuses while a rollover is in flight on this replica."""
        with self._cond:
            self._check_rollover_clear_locked()
            self.params = params
            if step is not None:
                self._params_step = step

    def _mem_swap_in(self, params, step: Optional[int]) -> None:
        """Ledger bookkeeping for a concluded swap: the candidate entry
        (if any) retires and the engine's serving entry re-registers
        with the new set — replacement releases the previously
        swapped-in set, so repeated rollovers hold a constant params
        footprint (the leak drill in tests/test_memory_ledger.py)."""
        led = memory_lib.ledger()
        led.release('params', self._mem_prefix + '/candidate')
        led.register('params', self._mem_prefix + '/serving', params,
                     owner=self, attrs={'step': step, 'state': 'serving'})

    def _mem_drop_candidate(self) -> None:
        memory_lib.ledger().release('params',
                                    self._mem_prefix + '/candidate')

    def _count_rollover(self, swapped: bool,
                        agreement: Optional[float]) -> None:
        if swapped:
            self.rollover_total.inc()
        else:
            self.rollover_rollbacks_total.inc()
            if self._tracer is not None:
                # a rollback is a postmortem moment: dump the recent
                # traces (incl. the canary_shadow tallies) while fresh
                self._tracer.dump_flight('rollover_rollback')
        if agreement is not None:
            self.rollover_agreement.set(agreement)
        if tele_core.enabled():
            reg = self._mirror
            reg.counter('serving/rollover_total' if swapped
                        else 'serving/rollover_rollbacks_total').inc()
            if agreement is not None:
                reg.gauge('serving/rollover_agreement').set(agreement)

    def _observe_canary(self, rollover: _Rollover, agree_rows: int,
                        rows: int, primary_s: float,
                        shadow_s: float) -> None:
        """Tally one shadow-scored batch; decide the rollover once the
        canary target is reached (decode-worker thread)."""
        decided = None
        with self._cond:
            if self._rollover is not rollover:
                return  # already decided (or cleared by close)
            rollover.batches += 1
            rollover.rows += rows
            rollover.agree_rows += agree_rows
            rollover.primary_fetch_s += primary_s
            rollover.shadow_fetch_s += shadow_s
            if rollover.batches >= rollover.target_batches:
                agreement = rollover.agree_rows / max(1, rollover.rows)
                swapped = agreement >= rollover.min_agreement
                if swapped:
                    self.params = rollover.params
                    if rollover.step is not None:
                        self._params_step = rollover.step
                self._rollover = None
                decided = (swapped, agreement)
        if decided is not None:
            swapped, agreement = decided
            if swapped:
                self._mem_swap_in(rollover.params, rollover.step)
            else:
                self._mem_drop_candidate()
            self._count_rollover(swapped, agreement)
            reason = ('canary passed' if swapped else
                      'agreement %.3f below floor %.2f'
                      % (agreement, rollover.min_agreement))
            self.log('serving: rollover %s (step %s): top-1 agreement '
                     '%.3f over %d rows in %d batches'
                     % ('SWAPPED' if swapped else 'ROLLED BACK',
                        rollover.step, agreement, rollover.rows,
                        rollover.batches))
            _resolve(rollover.handle, rollover.report(swapped, reason))

    def _fail_rollover(self, rollover: Optional[_Rollover],
                       exc: BaseException) -> None:
        if rollover is None:
            return
        with self._cond:
            if self._rollover is rollover:
                self._rollover = None
            elif rollover.handle.done():
                return
        self._mem_drop_candidate()
        if not rollover.handle.done():
            try:
                rollover.handle.set_exception(exc)
            except Exception:
                pass

    def follow_checkpoints(self, poll_secs: Optional[float] = None
                           ) -> 'ServingEngine':
        """Poll the checkpoint store for a newer retained step and roll
        it in through the canary (``--serve-follow-checkpoints``).
        Requires the engine's param source; idempotent."""
        if self._external:
            # the fleet must roll as ONE unit: N replica pollers racing
            # independent canaries is exactly the mode the mesh's
            # coordinated rollover exists to replace
            raise RuntimeError(
                'this engine is a mesh replica; --serve-follow-'
                'checkpoints runs at the mesh '
                '(ServingMesh.follow_checkpoints, serving/mesh.py)')
        if self._param_source is None:
            raise RuntimeError('follow_checkpoints needs a param source '
                               '(build the engine via '
                               'model.serving_engine())')
        poll = (poll_secs if poll_secs is not None
                else self.config.SERVE_FOLLOW_CHECKPOINTS_SECS)
        if poll <= 0:
            raise ValueError('follow_checkpoints needs poll_secs > 0 '
                             '(got %r)' % poll)
        with self._lock:
            # check-and-assign under the lock: concurrent calls must not
            # each see None and start duplicate poller threads (close()
            # only joins the one stored in _follow_thread)
            if self._closed:
                raise EngineClosed('ServingEngine is closed')
            if self._follow_thread is not None:
                return self
            self._follow_thread = threading.Thread(
                target=self._follow_loop, args=(poll,), daemon=True,
                name='serving-follow')
            self._follow_thread.start()
        return self

    def _follow_loop(self, poll_secs: float) -> None:
        attempted: Optional[int] = None  # this thread's memory only
        while not self._follow_stop.wait(poll_secs):
            try:
                newest = self._param_source.newest_step()
                with self._cond:
                    if self._closed:
                        return
                    busy = self._rollover is not None
                    current = self._params_step
                if newest is None or busy:
                    continue
                if attempted is not None and newest <= attempted:
                    continue  # don't hot-loop a rolled-back step
                if current is not None and newest <= current:
                    continue
                self.log('serving: follow-checkpoints found step %d; '
                         'starting canaried rollover' % newest)
                self.load_params(newest)
                # marked only once the restore+arm succeeded: a transient
                # load failure (poll racing an in-progress checkpoint
                # write, a filesystem blip) leaves the step eligible for
                # the next poll, while a canary rollback — which resolves
                # the handle, not this call — still won't be hot-looped
                attempted = newest
            except EngineClosed:
                return
            except Exception as exc:  # poller must survive blips
                self.log('serving: follow-checkpoints poll failed: %s'
                         % exc)

    def _set_queue_depth_locked(self) -> None:
        depth = sum(len(q) for q in self._queues.values())
        self.queue_depth.set(depth)
        if tele_core.enabled():
            self._mirror.gauge('serving/queue_depth').set(depth)

    # ------------------------------------------------------ dispatcher
    def _dispatch_loop(self) -> None:
        while True:
            abandoned: List[_Request] = []
            with self._cond:
                while not self._closed and \
                        not any(self._queues[t] for t in PREDICT_TIERS):
                    self._cond.wait()
                if self._closed and not self._drain:
                    # fail-fast close: queued work is going nowhere —
                    # every undispatched future fails typed below (the
                    # drain=True path instead falls through and keeps
                    # serving until the queues are empty)
                    for t in PREDICT_TIERS:
                        abandoned.extend(self._queues[t])
                        self._queues[t].clear()
                        self._pending_rows[t] = 0
                    self._set_queue_depth_locked()
                if self._closed and \
                        not any(self._queues[t] for t in PREDICT_TIERS):
                    done = True
                else:
                    done = False
            if abandoned or done:
                for request in abandoned:
                    request.fail(EngineClosed(
                        'ServingEngine closed with the request still '
                        'queued (close(drain=True) serves the queue '
                        'first)'))
                if done:
                    return
                continue
            with self._cond:
                if not any(self._queues[t] for t in PREDICT_TIERS):
                    continue  # raced a drain-close or expiry
                # serve the tier whose head request has waited longest
                tier = min(
                    (t for t in PREDICT_TIERS if self._queues[t]),
                    key=lambda t: self._queues[t][0].t_enqueue)
                deadline = (self._queues[tier][0].t_enqueue
                            + self.max_delay_s)
                max_bucket = self.buckets[-1]
                while not self._closed:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or \
                            self._pending_rows[tier] >= max_bucket:
                        break
                    self._cond.wait(remaining)
                if self._closed and not self._drain:
                    # a fail-fast close() landed during coalescing:
                    # the requests being gathered must fail typed at
                    # the top of the loop, not ride a final dispatch
                    continue
                taken: List[_Request] = []
                expired: List[_Request] = []
                rows = 0
                now = time.perf_counter()
                queue = self._queues[tier]
                while queue and rows + queue[0].rows <= max_bucket:
                    request = queue.popleft()
                    if request.t_deadline is not None \
                            and now >= request.t_deadline:
                        # expire instead of dispatching dead work: the
                        # client's SLO already passed while it queued
                        expired.append(request)
                        self._pending_rows[tier] -= request.rows
                        continue
                    taken.append(request)
                    rows += request.rows
                self._pending_rows[tier] -= rows
                self._set_queue_depth_locked()
            for request in expired:
                self.expired_total.inc()
                if tele_core.enabled():
                    self._mirror.counter(
                        'serving/expired_total').inc()
                request.fail(DeadlineExceeded(
                    'request expired after %.0fms in queue (SLO '
                    'deadline %.0fms)'
                    % (1e3 * (now - request.t_enqueue),
                       1e3 * (request.t_deadline - request.t_enqueue))))
            if taken:
                try:
                    self._dispatch_batch(tier, taken, rows)
                except BaseException as exc:  # keep the dispatcher alive
                    # OOM forensics at the jit-dispatch boundary
                    # (telemetry/memory.py): a RESOURCE_EXHAUSTED here
                    # dumps the attribution ledger before the typed
                    # failure reaches the callers
                    memory_lib.ledger().note_oom(exc, 'serving.dispatch')
                    for request in taken:
                        request.fail(exc)

    def dispatch_external(self, tier: str, taken: List[_Request],
                          rows: int) -> None:
        """Mesh-replica dispatch hook (serving/mesh.py): ship one
        coalesced micro-batch the mesh's shared front queue popped.
        Same failure contract as the internal dispatcher — an exception
        fails every member request typed and dumps OOM forensics — but
        it also RE-RAISES so the caller's replica breaker can count the
        failure and weight this replica out of dispatch."""
        try:
            self._dispatch_batch(tier, taken, rows)
        except BaseException as exc:
            memory_lib.ledger().note_oom(exc, 'serving.dispatch')
            for request in taken:
                request.fail(exc)
            raise

    def _pack_padded(self, padded: Batch, bucket: int) -> Tuple[tuple, int]:
        """Pad-complete plane batch -> packed wire arrays on a capacity
        rung from the warm ladder. Returns (arrays, capacity)."""
        ctx_rows, lengths = packed_lib.ragged_from_planes(
            padded.source, padded.path, padded.target, padded.mask)
        per_shard = int(packed_lib.shard_totals(
            lengths, self.data_axis).max(initial=0))
        capacity = pick_bucket(per_shard, self.capacities[bucket])
        ctx = packed_lib.pack_ragged(
            ctx_rows, lengths, self.trainer._token_pad,
            self.trainer._path_pad, data_shards=self.data_axis,
            capacity_minimum=capacity)
        return (ctx, lengths, np.ascontiguousarray(padded.label),
                np.ascontiguousarray(padded.weight)), capacity

    def _dispatch_batch(self, tier: str, taken: List[_Request],
                        rows: int) -> None:
        t0 = time.perf_counter()
        traced = [r for r in taken if r.trace is not None]
        for request in traced:
            if request.queue_span is not None:
                request.trace.end(request.queue_span, t0)
                request.queue_span = None
        stalled = faults.maybe_fire('slow_dispatch')
        if stalled:
            # deterministic overload: the queue keeps filling while the
            # dispatcher stalls here, driving shed/expiry/degrade drills
            time.sleep(faults.SLOW_DISPATCH_SECONDS)
        t_stall = time.perf_counter()
        merged = (taken[0].batch if len(taken) == 1 else
                  PathContextReader._concat([r.batch for r in taken]))
        bucket = pick_bucket(rows, self.buckets)
        padded = self.reader.pad_batch_to(merged, bucket)
        if self.wire == 'packed':
            host_arrays, capacity = self._pack_padded(padded, bucket)
        else:
            host_arrays, capacity = padded.device_arrays(), 0
        t_pack = time.perf_counter()
        arrays = mesh_lib.shard_batch(host_arrays, self.mesh,
                                      self.config.SHARD_CONTEXTS,
                                      direct=True)
        t_h2d = time.perf_counter()
        stale = None
        with self._lock:
            params = self.params
            rollover = self._rollover
            if rollover is not None and self.canary_timeout_s > 0 and \
                    time.perf_counter() - rollover.t_armed \
                    >= self.canary_timeout_s:
                # checked on EVERY tier's dispatches: vectors-only
                # traffic produces no top-1 comparisons, so a canary
                # armed on a mixed-tier engine could otherwise wedge
                # all later rollovers forever
                self._rollover = None
                stale, rollover = rollover, None
        if stale is not None:
            self._mem_drop_candidate()
            self._count_rollover(False, None)
            self.log('serving: rollover ROLLED BACK (step %s): canary '
                     'timed out after %.0fs with %d/%d batches scored '
                     '(no top-1-producing traffic?)'
                     % (stale.step, self.canary_timeout_s,
                        stale.batches, stale.target_batches))
            _resolve(stale.handle, stale.report(
                False, 'canary timed out after %.0fs'
                % self.canary_timeout_s))
        # async dispatch: returns with device futures; the decode pool
        # blocks on them, the dispatcher goes back to coalescing.  The
        # enqueue itself is serialized across engines (mesh replicas):
        # see _DISPATCH_ENQUEUE_LOCK
        with _DISPATCH_ENQUEUE_LOCK:
            if self._tracer is not None:
                # bridge into the profiler timeline (OBSERVABILITY.md):
                # the dispatch shows up as a named host lane next to the
                # trainer's StepTraceAnnotation scopes in captured traces
                import jax
                with jax.profiler.TraceAnnotation('serving/dispatch'):
                    out = self.trainer.predict_step_placed(params, arrays,
                                                           tier=tier)
            else:
                out = self.trainer.predict_step_placed(params, arrays,
                                                       tier=tier)
            shadow_out = None
            if rollover is not None and tier != 'vectors':
                # canary shadow: same arrays, same shapes/shardings —
                # the warm program is reused, so a live rollover never
                # compiles (predict programs are never donated:
                # re-feeding `arrays` is safe)
                shadow_out = self.trainer.predict_step_placed(
                    rollover.params, arrays, tier=tier)
        t_disp = time.perf_counter()
        if traced:
            t_head = min(request.t_enqueue for request in taken)
            # the pack span carries the dispatch attribution the latency
            # report keys on: bucket, effective tier, and — on a mesh —
            # WHICH replica served the batch (scripts/latency_report.py
            # per-replica columns)
            pack_attrs = {'bucket': bucket, 'capacity': capacity,
                          'batch_rows': rows, 'tier': tier}
            if self.replica_id is not None:
                pack_attrs['replica'] = self.replica_id
            for request in traced:
                tr, parent = request.trace, request.span_parent
                tr.span_at('serving.coalesce', t_head, t0, parent=parent,
                           attrs={'requests': len(taken),
                                  'overlaps': 'queue_wait'})
                if stalled:
                    tr.span_at('serving.stall', t0, t_stall,
                               parent=parent,
                               attrs={'fault': 'slow_dispatch'})
                tr.span_at('serving.pack', t_stall, t_pack, parent=parent,
                           attrs=pack_attrs)
                tr.span_at('serving.h2d', t_pack, t_h2d, parent=parent)
                tr.span_at('serving.dispatch', t_h2d, t_disp,
                           parent=parent,
                           attrs={'shadow': shadow_out is not None})
        dispatch_s = t_disp - t0
        self.dispatch_timer.record(dispatch_s)
        self.batches_total.inc()
        self.fill_rate.set(rows / bucket)
        self.last_dispatch = {'bucket': bucket, 'rows': rows,
                              'capacity': capacity,
                              'requests': len(taken)}
        if tele_core.enabled():
            reg = self._mirror
            reg.timer('serving/dispatch_ms').record(dispatch_s)
            reg.counter('serving/batches_total').inc()
            reg.gauge('serving/batch_fill_rate').set(rows / bucket)
        self._decode_pool.submit(self._decode, out, shadow_out, rollover,
                                 padded, taken, t_disp)

    # ----------------------------------------------------------- decode
    def _decode(self, out: dict, shadow_out: Optional[dict],
                rollover: Optional[_Rollover], padded: Batch,
                taken: List[_Request],
                t_dispatched: Optional[float] = None) -> None:
        try:
            t0 = time.perf_counter()
            # fetch ONLY the keys the tier produced (np.asarray blocks on
            # the device value — this is the worker pool's job, never the
            # dispatcher's)
            fetched = {key: np.asarray(value)
                       for key, value in out.items()}
            fetch_s = time.perf_counter() - t0
            n_rows = sum(request.rows for request in taken)
            results = decode_results(fetched, padded, n_rows,
                                     self.decode_table)
            decode_s = time.perf_counter() - t0
            self.decode_timer.record(decode_s)
            if tele_core.enabled():
                self._mirror.timer(
                    'serving/decode_ms').record(decode_s)
            t_fetch = t0 + fetch_s
            t_decode = t0 + decode_s
            row = 0
            now = time.perf_counter()
            for request in taken:
                deliver_span = None
                if request.trace is not None:
                    # record BEFORE deliver: the aggregate-completing
                    # chunk finishes the shared trace inside deliver(),
                    # and spans added after finish are dropped
                    tr, parent = request.trace, request.span_parent
                    # device time comes from the EXISTING async fetch
                    # boundary (the blocking np.asarray above): dispatch
                    # return -> fetch completion, never a new sync
                    dev = tr.span_at(
                        'serving.device_execute',
                        t_dispatched if t_dispatched is not None else t0,
                        t_fetch, parent=parent)
                    tr.span_at('serving.fetch', t0, t_fetch, parent=dev)
                    tr.span_at('serving.decode', t_fetch, t_decode,
                               parent=parent)
                    # deliver opens at decode end, so the wait behind
                    # earlier requests' sequential deliveries in this
                    # loop is attributed, not a phase gap
                    deliver_span = tr.span(
                        'serving.deliver', parent=parent, t0=t_decode,
                        attrs={'rows': request.rows})
                request.deliver(results[row:row + request.rows])
                row += request.rows
                latency = now - request.t_enqueue
                self.latency.record(latency)
                if tele_core.enabled():
                    self._mirror.timer(
                        'serving/latency_ms').record(latency)
                if request.trace is not None:
                    request.trace.end(deliver_span)
                    request.finish_trace()
            self._note_service(n_rows, taken)
            if self._on_batch_done is not None:
                # mesh replica-table hook: in-flight window release,
                # fleet drain estimate, dispatch-share accounting
                self._on_batch_done(self, n_rows, taken, True)
        except BaseException as exc:
            # async dispatches surface device OOM at this fetch
            # boundary — same forensics as the dispatch side
            memory_lib.ledger().note_oom(exc, 'serving.decode')
            for request in taken:
                request.fail(exc)
            if self._on_batch_done is not None:
                try:
                    self._on_batch_done(
                        self, sum(r.rows for r in taken), taken, False)
                except Exception:
                    pass  # the failure path must stay failure-proof
            return
        if shadow_out is not None:
            # canary tally AFTER the callers got their answers: the
            # shadow fetch never adds to request latency
            try:
                t1 = time.perf_counter()
                shadow_top = np.asarray(shadow_out['topk_indices'])
                shadow_s = time.perf_counter() - t1
                primary_top = fetched['topk_indices']
                agree = int(np.sum(primary_top[:n_rows, 0]
                                   == shadow_top[:n_rows, 0]))
                if self._tracer is not None:
                    self._tracer.single(
                        'serving.canary_shadow',
                        attrs={'step': rollover.step, 'rows': n_rows,
                               'agree_rows': agree,
                               'shadow_fetch_ms': 1e3 * shadow_s},
                        t0=t1, t1=t1 + shadow_s)
                self._observe_canary(rollover, agree, n_rows,
                                     fetch_s, shadow_s)
            except BaseException as exc:
                self._fail_rollover(rollover, exc)

    def _note_service(self, rows: int, taken: List[_Request]) -> None:
        """Feed the drain estimate with observed THROUGHPUT: rows
        delivered over a sliding window of recent batch completions.
        Unlike rows/sojourn this excludes queue wait (which scales with
        queue depth and would under-report a deep-but-draining queue by
        that factor, shedding deadlines the engine could in fact meet)
        and credits dispatch/decode pipelining; unlike a per-completion
        inter-arrival rate it aggregates across parallel decode
        workers, whose near-simultaneous completions would otherwise
        inflate the estimate by orders of magnitude and admit deadlines
        the queue cannot meet. Until the window spans a measurable
        interval (first batch, or right after an idle gap evicted it)
        the estimate seeds from batch sojourn — biased low, so a shed
        too many, never a deadline promised and missed."""
        oldest = min(request.t_enqueue for request in taken)
        with self._lock:
            self._service_window_rows, self._service_rows_per_s = \
                note_service_window(
                    self._service_window, self._service_window_rows,
                    self._service_rows_per_s, rows, oldest)

    # -------------------------------------------------------- lifecycle
    def stats(self) -> Dict[str, object]:
        """Snapshot of the engine's standalone instruments (latency
        percentiles come from the windowed Timer snapshots)."""
        with self._lock:
            peak_rows = self._peak_rows
            params_step = self._params_step
        return {
            'replica': self.replica_id,
            'requests_total': self.requests_total.snapshot(),
            'batches_total': self.batches_total.snapshot(),
            'queue_depth': self.queue_depth.snapshot(),
            'batch_fill_rate': self.fill_rate.snapshot(),
            'latency_ms': self.latency.snapshot(),
            'dispatch_ms': self.dispatch_timer.snapshot(),
            'decode_ms': self.decode_timer.snapshot(),
            'last_dispatch': self.last_dispatch,
            'shed_total': self.shed_total.snapshot(),
            'expired_total': self.expired_total.snapshot(),
            'degraded_total': self.degraded_total.snapshot(),
            'overload_level': self.overload_level_gauge.snapshot(),
            'queue_peak_rows': peak_rows,
            'rollover_total': self.rollover_total.snapshot(),
            'rollover_rollbacks_total':
                self.rollover_rollbacks_total.snapshot(),
            'params_step': params_step,
            'tracing': (self._tracer.stats()
                        if self._tracer is not None else None),
        }

    def close(self, drain: bool = False) -> None:
        """Stop the engine: new ``submit`` calls raise ``EngineClosed``.

        Default (fail-fast) close fails every still-queued request's
        future with a typed ``EngineClosed`` — nothing is left
        unresolved, and this replica stops serving immediately (the
        micro-batches already dispatched still deliver their results).
        ``close(drain=True)`` instead serves everything already admitted
        before stopping. An armed rollover's handle fails with
        ``EngineClosed`` either way. Idempotent; a second call (any
        mode) just waits for the first shutdown to finish."""
        with self._cond:
            already = self._closed
            if not already:
                self._closed = True
                self._drain = drain
            rollover, self._rollover = self._rollover, None
            self._cond.notify_all()
        self._follow_stop.set()
        if rollover is not None and not rollover.handle.done():
            try:
                rollover.handle.set_exception(EngineClosed(
                    'ServingEngine closed mid-canary (step %s)'
                    % rollover.step))
            except Exception:
                pass
        # every closer (not just the first) joins: a concurrent second
        # close() must not return while the dispatcher/decode workers
        # are still draining (join and shutdown(wait=True) are both
        # safe to call from multiple threads)
        follow = self._follow_thread
        if follow is not None:
            follow.join()
        if self._dispatcher is not None:
            self._dispatcher.join()
        self._decode_pool.shutdown(wait=True)
        # retire this engine's ledger entries: the params it swapped in
        # and an armed candidate (release is no-op-safe, so racing the
        # weakref finalizer is fine). The warm-ladder executables stay
        # registered on purpose — they live in the TRAINER's jit
        # caches, which a closed engine does not free
        led = memory_lib.ledger()
        led.release('params', self._mem_prefix + '/serving')
        led.release('params', self._mem_prefix + '/candidate')
        if self._tracer is not None and self._owns_tracer:
            # dispatcher + decode pool have drained: every in-flight
            # trace is already finished (delivered or typed-failed), so
            # the close dump is complete, never truncated.  An injected
            # tracer is NOT closed: its owner (the mesh sharing it
            # across replicas, a bench reading it afterwards) decides
            # when the fleet is actually done — a retiring replica must
            # not end the whole fleet's flight recorder
            self._tracer.close()

    def __enter__(self) -> 'ServingEngine':
        return self

    def __exit__(self, *exc) -> None:
        self.close()
