"""High-throughput serving engine: dynamic micro-batching over a fixed
ladder of warm, pre-compiled programs.

The naive serving shape — one ``model.predict`` per request — compiles a
fresh XLA program for every distinct request size, batches nothing
across requests, and computes + transfers attention weights and code
vectors even when the caller wants neither. TPU serving systems instead
coalesce ragged concurrent requests into a small set of pre-compiled
bucketed shapes and keep the device queue full (Ragged Paged Attention,
arxiv 2604.15464; Google's ads-serving infrastructure, arxiv 2501.10546
— PAPERS.md). This module is that shape for code2vec:

- **Bucket ladder.** Batch buckets (``Config.SERVING_BATCH_BUCKETS``,
  each rounded up to a multiple of the mesh data axis) × packed-capacity
  rungs (``data/packed.py::capacity_ladder`` — the eager-compile
  counterpart of training's StickyPacker bucketing) × output tiers
  (``training/trainer.py::PREDICT_TIERS``). ``warmup()`` compiles every
  program in the ladder at load, so steady-state serving never compiles
  (compile-counter-asserted in tests/test_serving_bench.py).
- **Dynamic micro-batcher.** ``submit()`` tokenizes on the caller thread
  and enqueues; a dispatcher thread coalesces concurrent requests under
  a max-latency deadline (``SERVING_MAX_DELAY_MS``) into the smallest
  covering batch bucket, packs them over the compact wire format
  (data/packed.py — the 0.24x bytes win applies directly to the h2d
  serving path), and dispatches asynchronously, so the device queue
  stays full while the NEXT batch coalesces.
- **Decode offload.** Host-side decode (device fetch, top-k word lookup,
  attention parsing) runs on a worker pool (``SERVING_DECODE_WORKERS``),
  so device dispatch never waits on Python.

Instrumented with standalone telemetry instruments (``stats()``) that
mirror into the process-global registry when telemetry is enabled
(``serving/*`` in telemetry/catalog.py; OBSERVABILITY.md).

Typical use::

    engine = model.serving_engine()          # warm-compiles the ladder
    future = engine.submit(context_lines)    # -> Future[list[results]]
    results = engine.predict(context_lines)  # sync convenience
    engine.close()                           # or `with model.serving_engine() as engine:`

SERVING.md has the architecture, the latency/throughput model, and the
runbook.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.data.reader import (Batch, EstimatorAction,
                                      PathContextReader)
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter, Gauge, Timer
from code2vec_tpu.training.trainer import PREDICT_TIERS


# --------------------------------------------------------------- ladder
def batch_ladder(buckets: Sequence[int], data_axis: int) -> Tuple[int, ...]:
    """Sorted, deduplicated batch buckets, each rounded UP to a multiple
    of the mesh data axis so every bucket shards evenly."""
    if data_axis < 1:
        raise ValueError('data_axis must be >= 1, got %d' % data_axis)
    out = set()
    for bucket in buckets:
        bucket = int(bucket)
        if bucket < 1:
            raise ValueError('batch buckets must be >= 1, got %d' % bucket)
        out.add(-(-bucket // data_axis) * data_axis)
    return tuple(sorted(out))


def pick_bucket(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket covering ``n`` rows, or None when ``n`` exceeds
    the ladder (callers split, or fall back to ad-hoc padding)."""
    for bucket in ladder:
        if bucket >= n:
            return bucket
    return None


def attention_per_context(source_strings, path_strings, target_strings,
                          attention_weights) -> Dict[Tuple[str, str, str],
                                                     float]:
    """Per-context attention dict, skipping padding contexts (reference
    model_base.py:115-129). Single definition — model_api and the engine
    decode both use it."""
    out: Dict[Tuple[str, str, str], float] = {}
    for source, path, target, weight in zip(
            source_strings, path_strings, target_strings,
            attention_weights):
        if not source and not path and not target:
            continue  # padding context
        out[(str(source), str(path), str(target))] = float(weight)
    return out


def decode_results(fetched: Dict[str, np.ndarray], batch: Batch,
                   n_rows: int, decode_table: np.ndarray) -> list:
    """Host numpy outputs + the (string-bearing) plane batch -> one
    ``ModelPredictionResults`` per row. Only the keys the tier produced
    are present in ``fetched``; absent tiers decode to empty/None."""
    # lazy: model_api imports this module (circularity-free direction)
    from code2vec_tpu.model_api import ModelPredictionResults
    topk_indices = fetched.get('topk_indices')
    topk_scores = fetched.get('topk_scores')
    attention = fetched.get('attention')
    code_vectors = fetched.get('code_vectors')
    results = []
    for r in range(n_rows):
        attn = {}
        if attention is not None and batch.source_strings is not None:
            attn = attention_per_context(
                batch.source_strings[r], batch.path_strings[r],
                batch.target_strings[r], attention[r])
        results.append(ModelPredictionResults(
            original_name=(str(batch.label_strings[r])
                           if batch.label_strings is not None else ''),
            topk_predicted_words=(list(decode_table[topk_indices[r]])
                                  if topk_indices is not None else []),
            topk_predicted_words_scores=(topk_scores[r]
                                         if topk_scores is not None
                                         else None),
            attention_per_context=attn,
            code_vector=(code_vectors[r]
                         if code_vectors is not None else None)))
    return results


# ------------------------------------------------------------- requests
def _resolve(future: Future, results: list) -> None:
    """set_result tolerating an already-done future: a caller may
    cancel() (these futures are never marked running, so cancel always
    succeeds) — its own result is then dropped, but delivery to the
    OTHER requests coalesced into the same micro-batch must proceed."""
    if not future.done():
        try:
            future.set_result(results)
        except Exception:
            pass  # lost the race to a concurrent cancel


class _Aggregate:
    """Joins the chunk results of one oversize request back into its
    caller-visible future, preserving row order."""

    # decode workers race on the chunk slots (lock-discipline rule,
    # ANALYSIS.md):
    # graftlint: guard _Aggregate.parts,left by lock
    def __init__(self, future: Future, n_chunks: int):
        self.future = future
        self.parts: List[Optional[list]] = [None] * n_chunks
        self.left = n_chunks
        self.lock = threading.Lock()

    def deliver(self, idx: int, results: list) -> None:
        with self.lock:
            self.parts[idx] = results
            self.left -= 1
            # snapshot under the lock: the last-chunk decider must not
            # re-read `parts` barehanded after releasing it
            done = list(self.parts) if self.left == 0 else None
        if done is not None:
            merged: list = []
            for part in done:
                merged.extend(part)
            _resolve(self.future, merged)

    def fail(self, exc: BaseException) -> None:
        # first failure wins; set_exception on a done future raises
        if not self.future.done():
            try:
                self.future.set_exception(exc)
            except Exception:
                pass


class _Request:
    """One queue entry: a tokenized chunk of <= max-bucket rows."""

    __slots__ = ('batch', 'rows', 'tier', 'future', 'aggregate',
                 'chunk_idx', 't_enqueue')

    def __init__(self, batch: Batch, tier: str,
                 future: Optional[Future] = None,
                 aggregate: Optional[_Aggregate] = None,
                 chunk_idx: int = 0):
        self.batch = batch
        self.rows = int(batch.label.shape[0])
        self.tier = tier
        self.future = future
        self.aggregate = aggregate
        self.chunk_idx = chunk_idx
        self.t_enqueue = time.perf_counter()

    def deliver(self, results: list) -> None:
        if self.aggregate is not None:
            self.aggregate.deliver(self.chunk_idx, results)
        else:
            _resolve(self.future, results)

    def fail(self, exc: BaseException) -> None:
        if self.aggregate is not None:
            self.aggregate.fail(exc)
        elif not self.future.done():
            self.future.set_exception(exc)


# --------------------------------------------------------------- engine
class ServingEngine:
    """Warm-compiled, micro-batching inference engine over a model's
    trainer + params. Build via ``Code2VecModel.serving_engine()``.

    Thread-safe: ``submit`` may be called from any number of threads;
    one dispatcher thread coalesces, ``decode_workers`` threads decode.
    """

    def __init__(self, config, trainer, params, vocabs,
                 decode_table: np.ndarray,
                 tiers: Optional[Sequence[str]] = None,
                 max_delay_ms: Optional[float] = None,
                 decode_workers: Optional[int] = None,
                 log=None):
        self.config = config
        self.trainer = trainer
        self.params = params
        self.decode_table = decode_table
        self.log = log if log is not None else (lambda msg: None)
        self.mesh = trainer.mesh
        self.data_axis = self.mesh.shape[mesh_lib.DATA_AXIS]
        # predict semantics: rows are never filtered; strings ride along
        # for the attention tiers' decode
        self.reader = PathContextReader(vocabs, config,
                                        EstimatorAction.Predict)
        import jax
        if jax.process_count() > 1:
            # per-host request queues cannot agree on batch contents
            # without a coordination layer; multi-host serving runs one
            # engine per host replica over that host's own mesh instead
            raise NotImplementedError(
                'ServingEngine is single-host only (runs on %d '
                'processes); serve one engine replica per host.'
                % jax.process_count())
        self.wire = config.wire_format_for(jax.process_count())
        self.buckets = batch_ladder(config.serving_batch_buckets,
                                    self.data_axis)
        # capacity rungs per bucket: a bucket's per-shard stream can hold
        # at most (bucket / data_axis) * MAX_CONTEXTS retained slots
        self.capacities: Dict[int, Tuple[int, ...]] = {
            bucket: packed_lib.capacity_ladder(
                (bucket // self.data_axis) * config.MAX_CONTEXTS)
            for bucket in self.buckets}
        tiers = tuple(tiers if tiers is not None
                      else config.serving_warm_tiers)
        for tier in tiers:
            if tier not in PREDICT_TIERS:
                raise ValueError('unknown tier %r; expected a subset of %s'
                                 % (tier, PREDICT_TIERS))
        self.tiers = tiers
        self.max_delay_s = (max_delay_ms if max_delay_ms is not None
                            else config.SERVING_MAX_DELAY_MS) / 1e3
        workers = (decode_workers if decode_workers is not None
                   else config.SERVING_DECODE_WORKERS)
        # standalone instruments: stats()/benchmarks read them without
        # enabling the process-global telemetry layer; emission sites
        # below mirror into the registry when telemetry is on
        self.latency = Timer('serving/latency_ms')
        self.dispatch_timer = Timer('serving/dispatch_ms')
        self.decode_timer = Timer('serving/decode_ms')
        self.requests_total = Counter('serving/requests_total')
        self.batches_total = Counter('serving/batches_total')
        self.queue_depth = Gauge('serving/queue_depth')
        self.fill_rate = Gauge('serving/batch_fill_rate')
        self.last_dispatch: Optional[Dict[str, int]] = None
        # submitters, the dispatcher, and close() share the queue state;
        # _cond wraps _lock, so holding either alias guards the fields
        # (lock-discipline rule, ANALYSIS.md):
        # graftlint: guard ServingEngine._queues,_pending_rows,_closed by _lock|_cond
        # graftlint: guard ServingEngine._warm by _warm_lock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, collections.deque] = {
            tier: collections.deque() for tier in PREDICT_TIERS}
        self._pending_rows: Dict[str, int] = {t: 0 for t in PREDICT_TIERS}
        self._closed = False
        self._warm = False
        self._index = None  # attach_index() arms submit_neighbors
        self._warm_lock = threading.Lock()
        self._decode_pool = ThreadPoolExecutor(
            max_workers=max(1, workers),
            thread_name_prefix='serving-decode')
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name='serving-dispatch')
        self._dispatcher.start()

    # ---------------------------------------------------------- warmup
    def _warm_batches(self, bucket: int):
        """Device-shaped zero batches for one bucket — every wire shape
        the dispatcher can produce for it (programs key on shapes, not
        values; all-PAD rows are valid model input)."""
        contexts = self.config.MAX_CONTEXTS
        if self.wire == 'packed':
            token_pad = self.trainer._token_pad
            path_pad = self.trainer._path_pad
            for cap in self.capacities[bucket]:
                ctx = np.empty((self.data_axis, cap, 3), np.int32)
                ctx[..., 0] = token_pad
                ctx[..., 1] = path_pad
                ctx[..., 2] = token_pad
                yield (ctx, np.zeros((bucket,), np.int32),
                       np.zeros((bucket,), np.int32),
                       np.zeros((bucket,), np.float32))
        else:
            yield (np.zeros((bucket, contexts), np.int32),
                   np.zeros((bucket, contexts), np.int32),
                   np.zeros((bucket, contexts), np.int32),
                   np.zeros((bucket, contexts), np.float32),
                   np.zeros((bucket,), np.int32),
                   np.zeros((bucket,), np.float32))

    def warmup(self) -> 'ServingEngine':
        """Eagerly compile every (bucket x capacity x tier) program in
        the ladder, so steady-state ``submit`` traffic never compiles.
        Idempotent; auto-invoked by the first ``submit`` if skipped."""
        import jax
        with self._warm_lock:
            if self._warm:
                return self
            t0 = time.perf_counter()
            programs = 0
            for bucket in self.buckets:
                for host_arrays in self._warm_batches(bucket):
                    arrays = mesh_lib.shard_batch(
                        host_arrays, self.mesh, self.config.SHARD_CONTEXTS,
                        direct=True)
                    for tier in self.tiers:
                        out = self.trainer.predict_step_placed(
                            self.params, arrays, tier=tier)
                        jax.block_until_ready(out)
                        programs += 1
            warm_s = time.perf_counter() - t0
            if tele_core.enabled():
                reg = tele_core.registry()
                reg.gauge('serving/warmup_s').set(warm_s)
                reg.gauge('serving/programs_warm').set(programs)
            self.log('serving: warmed %d programs (buckets %s x tiers %s, '
                     '%s wire) in %.1fs'
                     % (programs, list(self.buckets), list(self.tiers),
                        self.wire, warm_s))
            self._warm = True
        return self

    # ---------------------------------------------------------- submit
    def submit(self, context_lines: Sequence[str],
               tier: str = 'topk') -> Future:
        """Enqueue one prediction request (raw extractor/``.c2v`` context
        lines, like ``model.predict``). Returns a Future resolving to
        one ``ModelPredictionResults`` per line, in order. Requests
        larger than the top batch bucket are split transparently."""
        if tier not in self.tiers:
            raise ValueError('tier %r is not warmed on this engine '
                             '(tiers=%s)' % (tier, list(self.tiers)))
        # graftlint: disable=lock-discipline -- benign racy fast-fail: a close() racing past this read is re-checked under _cond before enqueue below
        if self._closed:
            raise RuntimeError('ServingEngine is closed')
        lines = list(context_lines)
        future: Future = Future()
        if not lines:
            future.set_result([])
            return future
        # graftlint: disable=lock-discipline -- benign racy read: warmup() is idempotent and re-checks _warm under _warm_lock
        if not self._warm:
            self.warmup()
        batch = self.reader.process_input_rows(lines)
        max_bucket = self.buckets[-1]
        n = len(lines)
        if n <= max_bucket:
            requests = [_Request(batch, tier, future=future)]
        else:
            n_chunks = -(-n // max_bucket)
            aggregate = _Aggregate(future, n_chunks)
            requests = [
                _Request(PathContextReader._take_rows(
                    batch, slice(i * max_bucket, (i + 1) * max_bucket)),
                    tier, aggregate=aggregate, chunk_idx=i)
                for i in range(n_chunks)]
        self.requests_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter('serving/requests_total').inc()
        with self._cond:
            if self._closed:
                raise RuntimeError('ServingEngine is closed')
            for request in requests:
                self._queues[tier].append(request)
                self._pending_rows[tier] += request.rows
            self._set_queue_depth_locked()
            self._cond.notify_all()
        return future

    def predict(self, context_lines: Sequence[str], tier: str = 'topk',
                timeout: Optional[float] = None) -> list:
        """Synchronous ``submit().result()`` convenience."""
        return self.submit(context_lines, tier).result(timeout)

    # -------------------------------------------------------- neighbors
    def attach_index(self, index) -> 'ServingEngine':
        """Arm ``submit_neighbors`` with a k-NN index over the corpus
        (code2vec_tpu/index/, INDEX.md). The engine must have the
        'vectors' tier warmed — neighbor queries ride it through the
        same micro-batching dispatcher as every other tier."""
        if 'vectors' not in self.tiers:
            raise ValueError(
                "submit_neighbors needs the 'vectors' tier warmed on "
                'this engine (tiers=%s); build it with '
                "tiers=('vectors', ...) or SERVING_WARM_TIERS."
                % list(self.tiers))
        self._index = index
        return self

    def submit_neighbors(self, context_or_vectors, k: Optional[int] = None
                         ) -> Future:
        """One warm round-trip from code to its nearest corpus methods:
        raw context lines (like ``submit``) ride the micro-batched
        'vectors' tier, and the resulting code vectors feed the attached
        index; an ``(n, D)`` vector array skips the predict leg. Returns
        a Future of one ``NeighborResult`` per input row, in order."""
        index = self._index
        if index is None:
            raise RuntimeError('no index attached — call '
                               'attach_index(load_index(...)) first '
                               '(code2vec_tpu/index/service.py)')
        k = k if k is not None else self.config.INDEX_NEIGHBORS_K
        from code2vec_tpu.index.service import neighbors_from_search
        outer: Future = Future()
        if isinstance(context_or_vectors, np.ndarray):
            vectors = np.atleast_2d(context_or_vectors)

            def lookup():
                try:
                    values, indices = index.search(vectors, k)
                    _resolve(outer, neighbors_from_search(
                        values, indices, index.labels))
                except BaseException as exc:
                    if not outer.done():
                        outer.set_exception(exc)
            self._decode_pool.submit(lookup)
            return outer
        inner = self.submit(context_or_vectors, tier='vectors')

        def chain(done: Future) -> None:
            # runs on the decode worker that resolved `inner` — the
            # index search stays off the dispatcher thread
            try:
                results = done.result()
                if not results:
                    _resolve(outer, [])
                    return
                vectors = np.stack([r.code_vector for r in results])
                values, indices = index.search(vectors, k)
                _resolve(outer, neighbors_from_search(
                    values, indices, index.labels))
            except BaseException as exc:
                if not outer.done():
                    outer.set_exception(exc)
        inner.add_done_callback(chain)
        return outer

    def predict_neighbors(self, context_or_vectors,
                          k: Optional[int] = None,
                          timeout: Optional[float] = None) -> list:
        """Synchronous ``submit_neighbors().result()`` convenience."""
        return self.submit_neighbors(context_or_vectors, k).result(timeout)

    def _set_queue_depth_locked(self) -> None:
        depth = sum(len(q) for q in self._queues.values())
        self.queue_depth.set(depth)
        if tele_core.enabled():
            tele_core.registry().gauge('serving/queue_depth').set(depth)

    # ------------------------------------------------------ dispatcher
    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and \
                        not any(self._queues[t] for t in PREDICT_TIERS):
                    self._cond.wait()
                if self._closed and \
                        not any(self._queues[t] for t in PREDICT_TIERS):
                    return
                # serve the tier whose head request has waited longest
                tier = min(
                    (t for t in PREDICT_TIERS if self._queues[t]),
                    key=lambda t: self._queues[t][0].t_enqueue)
                deadline = (self._queues[tier][0].t_enqueue
                            + self.max_delay_s)
                max_bucket = self.buckets[-1]
                while not self._closed:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or \
                            self._pending_rows[tier] >= max_bucket:
                        break
                    self._cond.wait(remaining)
                taken: List[_Request] = []
                rows = 0
                queue = self._queues[tier]
                while queue and rows + queue[0].rows <= max_bucket:
                    request = queue.popleft()
                    taken.append(request)
                    rows += request.rows
                self._pending_rows[tier] -= rows
                self._set_queue_depth_locked()
            if taken:
                try:
                    self._dispatch_batch(tier, taken, rows)
                except BaseException as exc:  # keep the dispatcher alive
                    for request in taken:
                        request.fail(exc)

    def _pack_padded(self, padded: Batch, bucket: int) -> Tuple[tuple, int]:
        """Pad-complete plane batch -> packed wire arrays on a capacity
        rung from the warm ladder. Returns (arrays, capacity)."""
        ctx_rows, lengths = packed_lib.ragged_from_planes(
            padded.source, padded.path, padded.target, padded.mask)
        per_shard = int(packed_lib.shard_totals(
            lengths, self.data_axis).max(initial=0))
        capacity = pick_bucket(per_shard, self.capacities[bucket])
        ctx = packed_lib.pack_ragged(
            ctx_rows, lengths, self.trainer._token_pad,
            self.trainer._path_pad, data_shards=self.data_axis,
            capacity_minimum=capacity)
        return (ctx, lengths, np.ascontiguousarray(padded.label),
                np.ascontiguousarray(padded.weight)), capacity

    def _dispatch_batch(self, tier: str, taken: List[_Request],
                        rows: int) -> None:
        t0 = time.perf_counter()
        merged = (taken[0].batch if len(taken) == 1 else
                  PathContextReader._concat([r.batch for r in taken]))
        bucket = pick_bucket(rows, self.buckets)
        padded = self.reader.pad_batch_to(merged, bucket)
        if self.wire == 'packed':
            host_arrays, capacity = self._pack_padded(padded, bucket)
        else:
            host_arrays, capacity = padded.device_arrays(), 0
        arrays = mesh_lib.shard_batch(host_arrays, self.mesh,
                                      self.config.SHARD_CONTEXTS,
                                      direct=True)
        # async dispatch: returns with device futures; the decode pool
        # blocks on them, the dispatcher goes back to coalescing
        out = self.trainer.predict_step_placed(self.params, arrays,
                                               tier=tier)
        dispatch_s = time.perf_counter() - t0
        self.dispatch_timer.record(dispatch_s)
        self.batches_total.inc()
        self.fill_rate.set(rows / bucket)
        self.last_dispatch = {'bucket': bucket, 'rows': rows,
                              'capacity': capacity,
                              'requests': len(taken)}
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.timer('serving/dispatch_ms').record(dispatch_s)
            reg.counter('serving/batches_total').inc()
            reg.gauge('serving/batch_fill_rate').set(rows / bucket)
        self._decode_pool.submit(self._decode, out, padded, taken)

    # ----------------------------------------------------------- decode
    def _decode(self, out: dict, padded: Batch,
                taken: List[_Request]) -> None:
        try:
            t0 = time.perf_counter()
            # fetch ONLY the keys the tier produced (np.asarray blocks on
            # the device value — this is the worker pool's job, never the
            # dispatcher's)
            fetched = {key: np.asarray(value)
                       for key, value in out.items()}
            n_rows = sum(request.rows for request in taken)
            results = decode_results(fetched, padded, n_rows,
                                     self.decode_table)
            decode_s = time.perf_counter() - t0
            self.decode_timer.record(decode_s)
            if tele_core.enabled():
                tele_core.registry().timer(
                    'serving/decode_ms').record(decode_s)
            row = 0
            now = time.perf_counter()
            for request in taken:
                request.deliver(results[row:row + request.rows])
                row += request.rows
                latency = now - request.t_enqueue
                self.latency.record(latency)
                if tele_core.enabled():
                    tele_core.registry().timer(
                        'serving/latency_ms').record(latency)
        except BaseException as exc:
            for request in taken:
                request.fail(exc)

    # -------------------------------------------------------- lifecycle
    def stats(self) -> Dict[str, object]:
        """Snapshot of the engine's standalone instruments (latency
        percentiles come from the windowed Timer snapshots)."""
        return {
            'requests_total': self.requests_total.snapshot(),
            'batches_total': self.batches_total.snapshot(),
            'queue_depth': self.queue_depth.snapshot(),
            'batch_fill_rate': self.fill_rate.snapshot(),
            'latency_ms': self.latency.snapshot(),
            'dispatch_ms': self.dispatch_timer.snapshot(),
            'decode_ms': self.decode_timer.snapshot(),
            'last_dispatch': self.last_dispatch,
        }

    def close(self) -> None:
        """Drain pending requests, stop the dispatcher and decode pool.
        Idempotent."""
        with self._cond:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
            self._cond.notify_all()
        if not already:
            self._dispatcher.join()
            self._decode_pool.shutdown(wait=True)

    def __enter__(self) -> 'ServingEngine':
        return self

    def __exit__(self, *exc) -> None:
        self.close()
