"""Mesh replica transports: one framed wire, two carriers (SERVING.md
"Multi-host mesh").

PR 13's process replicas spoke raw ``multiprocessing`` pickle over a
pipe: host-local by construction, and a worker dying mid-write could
leave a partial object that wedged or misparsed the parent's receiver.
This module factors the wire into a transport abstraction the mesh and
the worker both speak, with two properties the self-healing layer
needs:

- **One frame format, checksummed.**  Every message — dispatch,
  result, control, heartbeat — crosses as a length-prefixed frame::

      MAGIC(2) | length(4, big-endian) | crc32(4, big-endian) | payload

  where ``payload`` is the pickled message tuple.  ``decode_frame``
  validates magic, length, and CRC and raises a typed ``WireError`` on
  any mismatch, so a partial or corrupted frame fails the REPLICA
  typed instead of poisoning the stream (the parent treats it exactly
  like a worker death: redispatch + supervised restart).
- **Pipe and TCP carriers, identical protocol.**  ``PipeTransport``
  wraps the spawn pipe (``send_bytes``/``recv_bytes`` keep message
  boundaries; the frame adds integrity).  ``SocketTransport`` carries
  the same frames over TCP, so a replica worker can live on another
  machine: the mesh opens a ``SocketListener``, each worker DIALS IN
  and introduces itself with a ``hello`` frame (rid + wire protocol
  version + pid), then reports ``('ready', {params_step,
  capabilities})`` / ``('failed', reason)`` after its cold start — the
  same two-phase startup the pipe mode uses, so
  ``MESH_REPLICA_MODE=process|socket`` is a carrier choice, not a
  protocol fork.

The observability plane rides this wire too (ISSUE 15,
OBSERVABILITY.md "Fleet observability"): dispatch frames carry per-
member trace contexts, result frames and heartbeats carry finished
worker-side span records back, and the typed ``Heartbeat`` payload
(schema-versioned — a mismatched payload fails the replica typed, not
a pickle-shape guessing game) also snapshots the worker's telemetry
registry and memory-ledger buckets for the fleet merge.
``ClockOffset`` estimates each worker incarnation's monotonic-clock
offset so remote span stamps order correctly in the stitched tree.

Dependency-free above the serving errors; importable without jax (the
mesh's worker entry point imports the heavy stack, not this module).
"""
from __future__ import annotations

import dataclasses
import pickle
import select
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from code2vec_tpu.serving.errors import WireError

#: wire protocol version carried in the socket ``hello`` frame — a
#: parent refuses a worker speaking a different framing/message set
#: instead of misparsing it.  v2: dispatch frames carry trace contexts,
#: result frames carry span-record backhaul, heartbeats are the typed
#: schema-versioned ``Heartbeat`` payload.
WIRE_PROTO = 2

#: schema version of the ``Heartbeat`` payload.  Distinct from
#: WIRE_PROTO (which covers framing + the message set): the heartbeat
#: payload evolves faster than the wire, and a worker built against a
#: different payload shape must fail TYPED at the receiver instead of
#: feeding the telemetry merge garbage.
HEARTBEAT_SCHEMA = 1


@dataclasses.dataclass
class Heartbeat:
    """The worker -> mesh liveness payload (one per
    ``MESH_HEARTBEAT_SECS``), promoted from the old ad-hoc
    ``{'inflight': n}`` dict so new riders don't mean another
    pickle-shape guessing game at the listener:

    - ``inflight``: the worker's self-reported in-flight dispatch count
      (surfaced as ``worker_reported_inflight`` in ``mesh.stats()``);
    - ``t_mono``: the worker's ``time.perf_counter()`` at send time —
      one ``ClockOffset`` sample per beat, so the parent's offset
      estimate refreshes continuously;
    - ``spans``: finished worker-side span-record bundles not yet
      shipped on a result frame (spans orphaned by a crash-in-progress
      or finished after their result frame went out);
    - ``telemetry``: the worker's registry snapshot for the fleet
      merge (None when the worker runs telemetry-off);
    - ``ledger``: compact memory-ledger rollup ({attributed_bytes,
      budget_bytes, buckets}) so remote HBM pressure is visible in
      ``mesh.stats()`` BEFORE the worker OOMs.
    """
    schema: int = HEARTBEAT_SCHEMA
    inflight: int = 0
    t_mono: float = 0.0
    spans: List[dict] = dataclasses.field(default_factory=list)
    telemetry: Optional[Dict[str, object]] = None
    ledger: Optional[Dict[str, object]] = None


def check_heartbeat(payload) -> 'Heartbeat':
    """Validate one received heartbeat payload; raises ``WireError`` on
    a non-``Heartbeat`` object or a schema mismatch — the typed shape
    of version skew between a worker and its mesh."""
    if not isinstance(payload, Heartbeat):
        raise WireError('heartbeat payload is %s, not Heartbeat '
                        '(worker speaks a different payload schema)'
                        % type(payload).__name__)
    if payload.schema != HEARTBEAT_SCHEMA:
        raise WireError('heartbeat schema %r != expected %d (worker '
                        'built against a different payload version)'
                        % (payload.schema, HEARTBEAT_SCHEMA))
    return payload


class ClockOffset:
    """Per-worker-incarnation monotonic-clock offset estimate, so
    remote span stamps graft into the parent's timeline in the right
    order (OBSERVABILITY.md "Fleet observability").

    Each one-way sample (a frame stamped ``remote_t`` at send,
    received at ``local_t``) bounds the true offset from above:
    ``local_t = remote_t + offset_true + wire_delay`` with
    ``wire_delay >= 0``, so ``local_t - remote_t >= offset_true``.
    The estimate keeps the MINIMUM over samples — monotonically
    nonincreasing, converging to ``offset_true + min_delay`` — and is
    refreshed on every heartbeat (plus the ready handshake), so clock
    skew between hosts tightens rather than drifts.  Apply as
    ``t_parent = t_remote + offset``.
    """

    # samples arrive on the receiver thread while stitchers read the
    # estimate (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard ClockOffset._offset,_samples by _lock
    def __init__(self):
        self._lock = threading.Lock()
        self._offset: Optional[float] = None
        self._samples = 0

    def observe(self, remote_t: Optional[float],
                local_t: Optional[float] = None) -> None:
        """Feed one (remote send stamp, local receive stamp) sample."""
        if remote_t is None:
            return
        if local_t is None:
            local_t = time.perf_counter()
        sample = local_t - float(remote_t)
        with self._lock:
            self._samples += 1
            if self._offset is None or sample < self._offset:
                self._offset = sample

    @property
    def offset(self) -> float:
        """Current estimate in seconds (0.0 before any sample)."""
        with self._lock:
            return self._offset if self._offset is not None else 0.0

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

_MAGIC = b'c2'
# header layout: MAGIC (2 bytes) + length (4) + crc32 (4) = 10 bytes
_HEADER_LEN = 10
_LEN_CRC = struct.Struct('>II')

#: sanity bound on one frame: a corrupted length field must fail fast,
#: not allocate gigabytes.  Generous vs real traffic (a 1024-row packed
#: dispatch is ~MBs).
MAX_FRAME_BYTES = 1 << 30


def encode_frame(message) -> bytes:
    """Message tuple -> one framed byte string (pickle payload with a
    length + CRC32 header)."""
    payload = pickle.dumps(message)
    return (_MAGIC + _LEN_CRC.pack(len(payload),
                                   zlib.crc32(payload) & 0xFFFFFFFF)
            + payload)


def decode_frame(data: bytes):
    """One complete framed byte string -> message.  Raises ``WireError``
    on bad magic, truncation, trailing bytes, or CRC mismatch — the
    typed shape of a worker dying mid-write."""
    if len(data) < _HEADER_LEN:
        raise WireError('truncated frame: %d bytes < %d-byte header'
                        % (len(data), _HEADER_LEN))
    if data[:2] != _MAGIC:
        raise WireError('bad frame magic %r (stream corrupt or peer '
                        'speaks a different protocol)' % data[:2])
    length, crc = _LEN_CRC.unpack_from(data, 2)
    if length > MAX_FRAME_BYTES:
        raise WireError('frame length %d exceeds the %d-byte bound '
                        '(corrupted header)' % (length, MAX_FRAME_BYTES))
    payload = data[_HEADER_LEN:]
    if len(payload) != length:
        raise WireError('truncated frame: %d payload bytes, header '
                        'promised %d' % (len(payload), length))
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise WireError('frame CRC mismatch (worker died mid-write or '
                        'stream corrupt)')
    try:
        return pickle.loads(payload)
    except Exception as exc:
        raise WireError('frame payload failed to unpickle: %r' % exc)


class PipeTransport:
    """Framed messages over a ``multiprocessing`` connection.  The
    pipe keeps message boundaries; the frame adds the integrity check
    that turns a mid-write death into a typed ``WireError`` instead of
    a garbage object."""

    def __init__(self, conn):
        self._conn = conn

    def send(self, message) -> None:
        self._conn.send_bytes(encode_frame(message))

    def recv(self):
        """Blocking receive of one message.  Raises ``EOFError`` /
        ``OSError`` on a closed pipe, ``WireError`` on a bad frame."""
        return decode_frame(self._conn.recv_bytes())

    def poll(self, timeout: Optional[float] = 0.0) -> bool:
        return self._conn.poll(timeout)

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


class SocketTransport:
    """Framed messages over a connected TCP socket — the multi-host
    carrier.  ``recv`` reassembles exactly one frame from the byte
    stream (header first, then the promised payload); a short read
    inside a frame is a typed ``WireError``, a clean close at a frame
    boundary is ``EOFError`` (a worker death between messages)."""

    def __init__(self, sock: socket.socket):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not TCP (a unix socketpair in tests): no Nagle
        sock.settimeout(None)
        self._sock = sock

    def send(self, message) -> None:
        self._sock.sendall(encode_frame(message))

    def _read_exact(self, n: int, mid_frame: bool) -> bytes:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._sock.recv(min(remaining, 1 << 20))
            if not chunk:
                if chunks or mid_frame:
                    raise WireError(
                        'socket closed mid-frame (%d of %d bytes read)'
                        % (n - remaining, n))
                raise EOFError('socket closed')
            chunks.append(chunk)
            remaining -= len(chunk)
        return b''.join(chunks)

    def recv(self):
        header = self._read_exact(_HEADER_LEN, mid_frame=False)
        if header[:2] != _MAGIC:
            raise WireError('bad frame magic %r' % header[:2])
        length, _crc = _LEN_CRC.unpack_from(header, 2)
        if length > MAX_FRAME_BYTES:
            raise WireError('frame length %d exceeds the %d-byte bound'
                            % (length, MAX_FRAME_BYTES))
        return decode_frame(header + self._read_exact(length,
                                                      mid_frame=True))

    def poll(self, timeout: Optional[float] = 0.0) -> bool:
        try:
            ready, _w, _x = select.select([self._sock], [], [], timeout)
        except (OSError, ValueError):
            return True  # closed socket: recv will raise the real error
        return bool(ready)

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class SocketListener:
    """The mesh's accept side of socket mode: workers dial in, send a
    ``hello`` frame, and are claimed BY RID — so N workers can cold-
    start concurrently and connect in any order, and a worker on
    another machine only needs the (host, port) pair.

    Two dial-in classes (SERVING.md "Elastic fleet"): a rid the mesh
    ``expect()``ed (it spawned that worker) parks in ``_by_rid`` for
    ``claim()``; any OTHER rid is an externally-spawned worker
    (scripts/mesh_worker.py, launched by an orchestrator against a
    routable listener) and queues for ADOPTION — ``wait_adoptable()``
    hands it to the mesh's adoption loop instead of dropping it.  A
    hello speaking the wrong wire protocol is rejected TYPED: the
    worker receives an ``('adopt_rejected', reason)`` frame before the
    close, so a version-skewed orchestrator fleet learns why its
    workers never join instead of watching silent disconnects."""

    # the accept thread fills _by_rid/_adoptable while wait_ready
    # callers claim, the adoption loop pops, and close() tears it all
    # down (lock-discipline rule, ANALYSIS.md); _cond wraps _lock, so
    # holding either alias guards the fields:
    # graftlint: guard SocketListener._by_rid,_closed,_expected,_adoptable,_rejected by _lock|_cond
    def __init__(self, host: str = '127.0.0.1'):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self._sock.settimeout(0.25)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._by_rid: Dict[str, Tuple[SocketTransport, dict]] = {}
        self._expected: set = set()
        self._adoptable: List[Tuple[str, SocketTransport, dict]] = []
        self._rejected = 0
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name='mesh-listen')
        self._thread.start()

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us
            try:
                conn.settimeout(30.0)
                transport = SocketTransport(conn)
                hello = transport.recv()
                conn.settimeout(None)
                if hello[0] != 'hello':
                    raise WireError('bad worker hello %r' % (hello[:1],))
                if hello[2] != WIRE_PROTO:
                    # typed rejection frame BEFORE the close: the
                    # dial-in (an orchestrator-spawned worker built
                    # against another wire version) learns why it was
                    # refused instead of seeing a bare disconnect
                    with self._lock:
                        self._rejected += 1
                    try:
                        transport.send((
                            'adopt_rejected',
                            'wire proto %r != listener proto %d'
                            % (hello[2], WIRE_PROTO)))
                    except (OSError, WireError):
                        pass
                    raise WireError(
                        'bad worker hello %r (wire proto %d expected)'
                        % (hello[:3], WIRE_PROTO))
            except (WireError, EOFError, OSError, socket.timeout,
                    IndexError, TypeError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._cond:
                if self._closed:
                    transport.close()
                    return
                rid, info = hello[1], {'pid': hello[3]}
                if rid in self._expected:
                    self._by_rid[rid] = (transport, info)
                else:
                    # unclaimed rid: nobody here spawned this worker —
                    # park it for adoption rather than dropping it
                    self._adoptable.append((rid, transport, info))
                self._cond.notify_all()

    def expect(self, rid: str) -> None:
        """Register a rid THIS mesh is about to spawn, so its dial-in
        routes to ``claim()`` instead of the adoption queue.  Must run
        before the worker process starts (the dial can beat any later
        bookkeeping)."""
        with self._cond:
            self._expected.add(rid)

    def wait_adoptable(self, timeout: float,
                       cancel: Optional[threading.Event] = None
                       ) -> Optional[Tuple[str, SocketTransport, dict]]:
        """Block up to ``timeout`` for one externally-spawned dial-in;
        returns ``(rid, transport, info)`` or None (timeout, cancel, or
        listener closed)."""
        deadline = time.perf_counter() + timeout
        with self._cond:
            while True:
                if self._adoptable:
                    return self._adoptable.pop(0)
                if self._closed:
                    return None
                if cancel is not None and cancel.is_set():
                    return None
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return None
                self._cond.wait(min(remaining, 0.25))

    @property
    def rejected_total(self) -> int:
        """Dial-ins refused at the hello (wrong wire protocol)."""
        with self._lock:
            return self._rejected

    def claim(self, rid: str, timeout: float,
              cancel: Optional[threading.Event] = None,
              pid: Optional[int] = None
              ) -> Tuple[SocketTransport, dict]:
        """Block until the worker named ``rid`` has dialed in (its
        hello validated), then hand its transport over.  ``cancel``
        aborts the wait early (mesh close during a supervised
        restart).

        ``pid`` pins the claim to ONE worker incarnation: a reaped
        predecessor's late-arriving hello (same rid, dead process) is
        dropped instead of handed to the restart — claiming a corpse's
        socket would fail the attempt AND burn a restart-budget slot
        while the healthy new worker sits unclaimed."""
        deadline = time.perf_counter() + timeout
        while True:
            stale = None
            with self._cond:
                entry = self._by_rid.get(rid)
                if entry is not None and pid is not None and \
                        entry[1].get('pid') != pid:
                    stale = self._by_rid.pop(rid)
                    entry = None
                if entry is not None:
                    return self._by_rid.pop(rid)
                if self._closed:
                    raise EOFError('mesh socket listener closed while '
                                   'waiting for replica %s' % rid)
                if cancel is not None and cancel.is_set():
                    raise RuntimeError('wait for replica %s cancelled '
                                       '(mesh closing)' % rid)
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError(
                        'replica %s worker did not dial in within %.0fs'
                        % (rid, timeout))
                self._cond.wait(min(remaining, 0.25))
            if stale is not None:
                stale[0].close()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            unclaimed = list(self._by_rid.values())
            self._by_rid.clear()
            unclaimed.extend((t, info) for _rid, t, info
                             in self._adoptable)
            self._adoptable.clear()
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=10.0)
        for transport, _info in unclaimed:
            transport.close()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed


def dial(address: Tuple[str, int], rid: str, pid: int,
         timeout: float = 30.0, attempts: int = 3) -> SocketTransport:
    """Worker side of socket mode: connect to the mesh listener and
    introduce this replica (``hello`` carries rid + wire protocol +
    pid; ``ready``/``failed`` with params-step and capabilities follow
    after the cold start)."""
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            sock = socket.create_connection(tuple(address),
                                            timeout=timeout)
            transport = SocketTransport(sock)
            transport.send(('hello', rid, WIRE_PROTO, pid))
            return transport
        except OSError as exc:
            last = exc
            time.sleep(0.2 * (2 ** attempt))
    raise RuntimeError('replica %s could not dial the mesh listener at '
                       '%s: %r' % (rid, address, last))
