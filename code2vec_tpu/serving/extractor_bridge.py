"""Bridge to an out-of-process path-context extractor.

TPU-native equivalent of the reference's ``extractor.py``: shells out to an
extractor CLI per request (reference ran
``java -cp JAR JavaExtractor.App --no_hash`` per REPL turn, extractor.py:12-19),
truncates to MAX_CONTEXTS (head-truncation at predict time, :27), and
re-hashes path strings with a Java ``String#hashCode`` clone to build the
hash→string dict used to display attention paths (:40-49).

The extractor command is pluggable: the native C++ extractor shipped with
this framework (``extractor/build/c2v-extract``), a reference-compatible JAR,
or anything flag-compatible with them.

Hardened for serving traffic (SERVING.md "Overload & rollover runbook"):

- every invocation carries a **timeout** (``EXTRACTOR_TIMEOUT_SECS``,
  ``--extractor-timeout``) — a wedged JVM/parser kills the call, not the
  caller — and failures surface the child's stderr;
- infrastructure failures (spawn, nonzero/signal exit, timeout) raise the
  typed ``ExtractorCrash``, distinct from the clean "no paths in this
  input" ``ValueError`` — only the former is worth retrying;
- ``ExtractorPool`` runs calls on persistent worker threads with bounded
  concurrency, retry-with-backoff on crash, and a circuit breaker that
  fails fast (``ExtractorUnavailable``) while the extractor is known-bad,
  instead of stacking doomed subprocess spawns under load.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import common
from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving.errors import (ExtractorCrash,
                                         ExtractorUnavailable)
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter, Gauge

_NATIVE_EXTRACTOR_CANDIDATES = (
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), 'extractor', 'build', 'c2v-extract'),
    'c2v-extract',
)


def find_default_extractor() -> Optional[List[str]]:
    """Locate the native extractor binary (preferred) or a reference JAR."""
    for candidate in _NATIVE_EXTRACTOR_CANDIDATES:
        path = shutil.which(candidate) or (
            candidate if os.path.isfile(candidate)
            and os.access(candidate, os.X_OK) else None)
        if path:
            return [path]
    jar = os.environ.get('CODE2VEC_EXTRACTOR_JAR')
    if jar and os.path.isfile(jar):
        return ['java', '-cp', jar, 'JavaExtractor.App']
    return None


#: source extensions the serving stack recognizes — language inference
#: is BY EXTENSION and is the default everywhere (predict entry point,
#: extractor invocation); the reference reached C# only via explicit
#: flags
_EXT_LANGS = {'.java': 'java', '.cs': 'csharp'}


def infer_language(path: str) -> Optional[str]:
    """'java' / 'csharp' from the file extension; None when unknown
    (the extractor then falls back to its own default frontend)."""
    return _EXT_LANGS.get(os.path.splitext(path)[1].lower())


def _stderr_of(proc_or_exc) -> str:
    """Best-effort stderr text from a CompletedProcess or a
    TimeoutExpired (whose captured output may be bytes or None)."""
    stderr = getattr(proc_or_exc, 'stderr', None)
    if isinstance(stderr, bytes):
        stderr = stderr.decode('utf-8', 'replace')
    return (stderr or '').strip()


class Extractor:
    def __init__(self, config: Config,
                 extractor_command: Optional[List[str]] = None,
                 max_path_length: int = 8, max_path_width: int = 2,
                 timeout_secs: Optional[float] = None):
        self.config = config
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        # 0 disables (debugger-friendly); the config default bounds every
        # serving-path call so a wedged extractor cannot hang the caller
        self.timeout_secs = (timeout_secs if timeout_secs is not None
                             else config.EXTRACTOR_TIMEOUT_SECS)
        self.command = extractor_command or find_default_extractor()
        if self.command is None:
            raise RuntimeError(
                'No path-context extractor found. Build the native one '
                '(extractor/README.md) or set CODE2VEC_EXTRACTOR_JAR.')

    def extract_paths(self, input_path: str
                      ) -> Tuple[List[str], Dict[str, str]]:
        """Run the extractor on one source file.

        Returns (prediction-ready context lines with hashed paths,
        hash→path-string dict for display) — reference extractor.py:12-49.
        Raises ``ExtractorCrash`` on spawn/exit/timeout failures (stderr
        included) and plain ``ValueError`` when the input simply yields
        no paths.
        """
        command = self.command + [
            '--max_path_length', str(self.max_path_length),
            '--max_path_width', str(self.max_path_width),
            '--file', input_path, '--no_hash']
        # language inference from the extension is the DEFAULT: a .cs
        # input selects the C# frontend without any caller flag.  Only
        # non-java is made explicit — the reference-JAR fallback
        # (JavaExtractor.App) rejects --lang, and Java is every
        # frontend's default anyway.
        lang = infer_language(input_path)
        if lang is not None and lang != 'java':
            command += ['--lang', lang]
        timeout = self.timeout_secs if self.timeout_secs > 0 else None
        try:
            proc = subprocess.run(command, capture_output=True, text=True,
                                  timeout=timeout)
        except subprocess.TimeoutExpired as e:
            stderr = _stderr_of(e)
            raise ExtractorCrash(
                'extractor %r timed out after %gs on `%s`%s'
                % (self.command, timeout, input_path,
                   ': ' + stderr if stderr else ''))
        except OSError as e:
            raise ExtractorCrash('failed to run extractor %r: %s'
                                 % (self.command, e))
        if proc.returncode != 0:
            stderr = _stderr_of(proc)
            raise ExtractorCrash(
                stderr or 'extractor failed with code %d' % proc.returncode)
        output_lines = [line for line in proc.stdout.splitlines()
                        if line.strip()]
        if not output_lines:
            # a clean run with no extractable methods is a CONTENT error
            # (bad input file), not an extractor failure: never retried,
            # never counted against the circuit breaker
            raise ValueError('cannot extract any paths from the input file'
                             + (': ' + _stderr_of(proc)
                                if _stderr_of(proc) else ''))

        # keyed by the DECIMAL STRING of the hash: attention contexts come
        # back from the model as strings (reference extractor.py:32-33)
        hash_to_string: Dict[str, str] = {}
        result: List[str] = []
        for line in output_lines:
            parts = line.rstrip().split(' ')
            method_name = parts[0]
            contexts = parts[1:self.config.MAX_CONTEXTS + 1]  # head-truncate
            hashed_contexts = []
            for context in contexts:
                pieces = context.split(',')
                if len(pieces) != 3:
                    continue
                source, path_string, target = pieces
                hashed_path = str(common.java_string_hashcode(path_string))
                hash_to_string[hashed_path] = path_string
                hashed_contexts.append(
                    '%s,%s,%s' % (source, hashed_path, target))
            padding = ' ' * (self.config.MAX_CONTEXTS - len(hashed_contexts))
            result.append(method_name + ' ' + ' '.join(hashed_contexts)
                          + padding)
        return result, hash_to_string


# breaker-state gauge encoding (serving/breaker_state)
_CLOSED, _HALF_OPEN, _OPEN = 0, 1, 2
_STATE_NAMES = {_CLOSED: 'closed', _HALF_OPEN: 'half-open', _OPEN: 'open'}


class ExtractorPool:
    """Persistent pooled extractor workers for raw-source serving
    traffic: bounded concurrency, per-call timeout (via ``Extractor``),
    retry-with-backoff on crash, and a circuit breaker.

    Breaker protocol (the classic three states):

    - **closed** — calls flow; ``EXTRACTOR_BREAKER_THRESHOLD``
      consecutive crashed calls (each already retried
      ``EXTRACTOR_RETRIES`` times) trip it open;
    - **open** — every call fails fast with ``ExtractorUnavailable``
      (no subprocess spawn, no timeout wait) until
      ``EXTRACTOR_BREAKER_COOLDOWN_SECS`` elapses;
    - **half-open** — ONE probe call runs for real (concurrent calls
      keep failing fast); success closes the breaker, failure re-opens
      it and restarts the cooldown.

    Thread-safe; ``submit`` returns a Future, ``extract_paths`` is the
    sync convenience. Use as a context manager or call ``close()``.
    """

    # workers, callers, and the breaker transition race on this state
    # (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard ExtractorPool._state,_failures,_opened_at,_probing by _lock
    def __init__(self, config: Config,
                 extractor_command: Optional[List[str]] = None,
                 workers: Optional[int] = None, log=None, tracer=None,
                 **extractor_kw):
        self.config = config
        self.log = log if log is not None else (lambda msg: None)
        # optional telemetry/tracing.py Tracer: every pool call then
        # gets an `extractor.call` span (attempt count, breaker state),
        # and a breaker-open transition dumps the flight recorder
        self.tracer = tracer
        self.extractor = Extractor(config, extractor_command,
                                   **extractor_kw)
        self.retries = config.EXTRACTOR_RETRIES
        self.backoff_secs = config.EXTRACTOR_BACKOFF_SECS
        self.breaker_threshold = config.EXTRACTOR_BREAKER_THRESHOLD
        self.breaker_cooldown_secs = config.EXTRACTOR_BREAKER_COOLDOWN_SECS
        self.retries_total = Counter('serving/extractor_retries_total')
        self.breaker_open_total = Counter('serving/breaker_open_total')
        self.breaker_state = Gauge('serving/breaker_state')
        self._lock = threading.Lock()
        self._state = _CLOSED
        self._failures = 0        # consecutive crashed calls
        self._opened_at = 0.0
        self._probing = False     # a half-open probe is in flight
        workers = (workers if workers is not None
                   else config.EXTRACTOR_POOL_WORKERS)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers),
            thread_name_prefix='extractor')

    # ------------------------------------------------------------ breaker
    def state(self) -> str:
        """'closed' | 'half-open' | 'open' (for runbooks/tests)."""
        with self._lock:
            return _STATE_NAMES[self._state]

    def _set_state_locked(self, state: int) -> None:
        self._state = state
        self.breaker_state.set(state)
        if tele_core.enabled():
            tele_core.registry().gauge('serving/breaker_state').set(state)

    def _admit(self) -> Optional[bool]:
        """Breaker gate for one call: None = rejected (fail fast),
        False = a normal admitted call, True = this call OWNS the single
        half-open probe slot. Ownership travels with the call so a
        straggler admitted while the breaker was still closed can never
        release (or be judged as) a probe it does not hold."""
        with self._lock:
            if self._state == _CLOSED:
                return False
            if self._state == _OPEN:
                if time.monotonic() - self._opened_at \
                        < self.breaker_cooldown_secs:
                    return None
                self._set_state_locked(_HALF_OPEN)
                self._probing = True
                return True
            # half-open: exactly one probe at a time
            if self._probing:
                return None
            self._probing = True
            return True

    def _on_success(self, probe: bool) -> None:
        with self._lock:
            self._failures = 0
            recovered = False
            if probe:
                self._probing = False
                if self._state != _CLOSED:
                    recovered = True
                    self._set_state_locked(_CLOSED)
        if recovered:
            self.log('extractor breaker: probe succeeded, closed')

    def _on_crash(self, probe: bool) -> None:
        with self._lock:
            self._failures += 1
            if probe:
                self._probing = False
            trip = (probe and self._state == _HALF_OPEN) or \
                self._failures >= self.breaker_threshold
            if trip and self._state != _OPEN:
                self._set_state_locked(_OPEN)
                self._opened_at = time.monotonic()
                self.breaker_open_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'serving/breaker_open_total').inc()
            else:
                trip = False
        if trip:
            self.log('extractor breaker: OPEN after %d consecutive '
                     'crashes (cooldown %gs)'
                     % (self.breaker_threshold, self.breaker_cooldown_secs))
            if self.tracer is not None:
                # the traces leading into the trip are the postmortem
                self.tracer.dump_flight('breaker_open')

    def _release_probe(self, probe: bool) -> None:
        """Unwind path for exceptions OUTSIDE the crash/content
        taxonomy (MemoryError, a parsing bug, ...): give the probe slot
        back without judging the extractor, so one weird error cannot
        wedge the breaker in half-open forever."""
        if not probe:
            return
        with self._lock:
            self._probing = False

    # -------------------------------------------------------------- calls
    def _call(self, input_path: str) -> Tuple[List[str], Dict[str, str]]:
        trace = None
        if self.tracer is not None:
            trace = self.tracer.begin(
                'extractor.call',
                attrs={'input': os.path.basename(input_path),
                       'breaker': self.state()})
        probe = self._admit()
        if probe is None:
            exc = ExtractorUnavailable(
                'extractor circuit breaker is %s (cooldown %gs after %d '
                'consecutive crashes); failing fast'
                % (self.state(), self.breaker_cooldown_secs,
                   self.breaker_threshold))
            if trace is not None:
                trace.finish(status='unavailable', reason=str(exc))
            raise exc
        last_crash: Optional[ExtractorCrash] = None
        attempts = 0
        try:
            for attempt in range(self.retries + 1):
                attempts = attempt + 1
                if attempt:
                    self.retries_total.inc()
                    if tele_core.enabled():
                        tele_core.registry().counter(
                            'serving/extractor_retries_total').inc()
                    time.sleep(self.backoff_secs * (2 ** (attempt - 1)))
                try:
                    if faults.maybe_fire('extractor_crash'):
                        raise ExtractorCrash(
                            'FAULT_INJECT: injected extractor crash')
                    out = self.extractor.extract_paths(input_path)
                except ExtractorCrash as crash:
                    last_crash = crash
                    continue
                except ValueError as content:
                    # content error: the extractor itself is healthy
                    self._on_success(probe)
                    if trace is not None:
                        trace.root.attrs['attempts'] = attempts
                        trace.finish(status='content_error',
                                     reason=str(content))
                    raise
                self._on_success(probe)
                if trace is not None:
                    trace.root.attrs['attempts'] = attempts
                    trace.finish(status='ok')
                return out
        except (ExtractorCrash, ValueError):
            raise
        except BaseException as exc:
            self._release_probe(probe)
            if trace is not None:
                trace.finish(status='error', reason=repr(exc))
            raise
        self._on_crash(probe)
        if trace is not None:
            trace.root.attrs['attempts'] = attempts
            trace.root.attrs['breaker_after'] = self.state()
            trace.finish(status='crash', reason=str(last_crash))
        raise last_crash

    def submit(self, input_path: str) -> Future:
        """Extract on a pool worker; Future of (lines, hash→path)."""
        return self._pool.submit(self._call, input_path)

    def extract_paths(self, input_path: str,
                      timeout: Optional[float] = None
                      ) -> Tuple[List[str], Dict[str, str]]:
        """Synchronous ``submit().result()`` convenience — drop-in for
        ``Extractor.extract_paths`` with the pool's resilience."""
        return self.submit(input_path).result(timeout)

    # ---------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> 'ExtractorPool':
        return self

    def __exit__(self, *exc) -> None:
        self.close()
