"""Bridge to an out-of-process path-context extractor.

TPU-native equivalent of the reference's ``extractor.py``: shells out to an
extractor CLI per request (reference ran
``java -cp JAR JavaExtractor.App --no_hash`` per REPL turn, extractor.py:12-19),
truncates to MAX_CONTEXTS (head-truncation at predict time, :27), and
re-hashes path strings with a Java ``String#hashCode`` clone to build the
hash→string dict used to display attention paths (:40-49).

The extractor command is pluggable: the native C++ extractor shipped with
this framework (``extractor/build/c2v-extract``), a reference-compatible JAR,
or anything flag-compatible with them.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import common
from code2vec_tpu.config import Config

_NATIVE_EXTRACTOR_CANDIDATES = (
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), 'extractor', 'build', 'c2v-extract'),
    'c2v-extract',
)


def find_default_extractor() -> Optional[List[str]]:
    """Locate the native extractor binary (preferred) or a reference JAR."""
    for candidate in _NATIVE_EXTRACTOR_CANDIDATES:
        path = shutil.which(candidate) or (
            candidate if os.path.isfile(candidate)
            and os.access(candidate, os.X_OK) else None)
        if path:
            return [path]
    jar = os.environ.get('CODE2VEC_EXTRACTOR_JAR')
    if jar and os.path.isfile(jar):
        return ['java', '-cp', jar, 'JavaExtractor.App']
    return None


class Extractor:
    def __init__(self, config: Config,
                 extractor_command: Optional[List[str]] = None,
                 max_path_length: int = 8, max_path_width: int = 2):
        self.config = config
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        self.command = extractor_command or find_default_extractor()
        if self.command is None:
            raise RuntimeError(
                'No path-context extractor found. Build the native one '
                '(extractor/README.md) or set CODE2VEC_EXTRACTOR_JAR.')

    def extract_paths(self, input_path: str
                      ) -> Tuple[List[str], Dict[str, str]]:
        """Run the extractor on one source file.

        Returns (prediction-ready context lines with hashed paths,
        hash→path-string dict for display) — reference extractor.py:12-49.
        """
        command = self.command + [
            '--max_path_length', str(self.max_path_length),
            '--max_path_width', str(self.max_path_width),
            '--file', input_path, '--no_hash']
        try:
            proc = subprocess.run(command, capture_output=True, text=True)
        except OSError as e:
            # surfaced as ValueError so the REPL loop reports and continues
            raise ValueError('failed to run extractor %r: %s'
                             % (self.command, e))
        if proc.returncode != 0:
            raise ValueError(proc.stderr.strip()
                             or 'extractor failed with code %d'
                             % proc.returncode)
        output_lines = [line for line in proc.stdout.splitlines()
                        if line.strip()]
        if not output_lines:
            raise ValueError('cannot extract any paths from the input file'
                             + (': ' + proc.stderr.strip()
                                if proc.stderr.strip() else ''))

        # keyed by the DECIMAL STRING of the hash: attention contexts come
        # back from the model as strings (reference extractor.py:32-33)
        hash_to_string: Dict[str, str] = {}
        result: List[str] = []
        for line in output_lines:
            parts = line.rstrip().split(' ')
            method_name = parts[0]
            contexts = parts[1:self.config.MAX_CONTEXTS + 1]  # head-truncate
            hashed_contexts = []
            for context in contexts:
                pieces = context.split(',')
                if len(pieces) != 3:
                    continue
                source, path_string, target = pieces
                hashed_path = str(common.java_string_hashcode(path_string))
                hash_to_string[hashed_path] = path_string
                hashed_contexts.append(
                    '%s,%s,%s' % (source, hashed_path, target))
            padding = ' ' * (self.config.MAX_CONTEXTS - len(hashed_contexts))
            result.append(method_name + ' ' + ' '.join(hashed_contexts)
                          + padding)
        return result, hash_to_string
