"""Serving mesh: N ``ServingEngine`` replicas behind ONE shared front
queue, with continuous cross-tier batching, replica-aware admission,
and coordinated canaried rollover (SERVING.md "Serving mesh").

The single-engine story (PRs 4/7/8/9) ends at one replica: "heavy
traffic from millions of users" (ROADMAP north star) needs a FLEET —
the Ads-serving stack's shape (PAPERS.md, arxiv 2501.10546): many model
servers behind shared queues, params refreshed continuously under live
traffic.  This module is that shape for code2vec:

- **One shared front queue** (``serving/frontqueue.py``).  Admission —
  bound, deadline-vs-drain, degradation ladder — moves up to the fleet:
  the drain estimate is the fleet service rate (the mesh's sliding
  window over every replica's completions — numerically the sum of
  per-replica served-rows/s), and shedding/expiry are typed at the
  shared queue, so one slow replica never wedges its share of traffic.
- **Replica pullers = continuous cross-tier batching.**  Each replica
  runs one puller thread that claims work from the shared queue the
  moment the replica has a free in-flight slot: the puller picks the
  tier whose head waited longest and keeps folding newly-arriving
  compatible requests into the still-gathering micro-batch up to the
  coalescing deadline (the Ragged Paged Attention
  insert-into-the-in-flight-batch idea at request granularity), then
  packs onto the smallest covering (bucket x capacity-rung x tier)
  warm program of ITS engine.  Predict tiers and ``submit_neighbors``
  vectors traffic ride the same dispatch stream.
- **Replica-aware weighting.**  The replica table tracks per-replica
  in-flight windows, a dispatch circuit breaker (K consecutive dispatch
  failures open it; half-open probes one batch after the cooldown), and
  retirement — a breaker-open or retired replica simply stops pulling,
  and the queue redirects to its siblings instead of wedging.  A
  replica canarying a rollover pulls with a halved in-flight window
  (it still needs live traffic to conclude the canary; its shadow cost
  is off-latency by the engine's contract).
- **Coordinated rollover.**  ``load_params(step|path|pytree)`` canaries
  on ONE replica (reusing the engine's shadow-scoring machinery), then
  fleet-swaps the SAME validated params onto every other replica on
  agreement (``engine.adopt_params`` — pointer swap, zero compiles,
  one ledger entry), or rolls the canary back and leaves every replica
  serving the old params.  ``follow_checkpoints`` moves up here too:
  the fleet rolls as a unit instead of N pollers racing.

**Replica modes.**  ``MESH_REPLICAS`` in-process replica threads by
default (``MESH_REPLICA_MODE='thread'``): every replica is a
``ServingEngine`` in external-dispatch mode over the model's trainer,
so warm programs are shared through the trainer's jit caches and
replica 2..N warm for free.  ``'process'`` runs each replica as a
spawned worker process hosting its own model + engine, speaking the
framed dispatch wire (serving/transport.py: tokenized ``Batch`` out,
decoded results back, every message length-prefixed + CRC-checked)
over a pipe; ``'socket'`` carries the IDENTICAL protocol over TCP — the
mesh opens a listener, each worker dials in with a rid/proto handshake
and reports its restored params step, so replicas can live on other
machines.  Worker replicas restore params from the model's checkpoint
path (pytrees don't cross processes; checkpoint refs do — which is
also why worker-mode rollover takes step/path sources only).

**Self-healing (SERVING.md "Multi-host mesh").**  Replica death is a
non-event, not an operator page:

- **Liveness distinct from dispatch health.**  Workers heartbeat every
  ``MESH_HEARTBEAT_SECS`` (the in-flight count rides along); a
  worker that misses more than ``MESH_HEARTBEAT_MISSES`` intervals is
  marked dead typed — catching the hung or network-partitioned worker
  the dispatch breaker cannot see because nothing is in flight.
- **Crash-safe redispatch.**  Requests popped into a batch that dies
  with its worker are re-admitted ONCE at the FRONT of the shared
  queue with the dead incarnation excluded and their deadlines intact
  (already-expired members still shed typed at pop), so a crash costs
  latency, not answers; a second crash fails them typed
  (``ReplicaDead``).  The redispatched request's trace carries both
  attempts (``serving.redispatch`` event + a second queue_wait span).
- **Supervised restart.**  A mesh supervisor thread restarts a dead
  locally-spawned worker with exponential backoff under a window-
  scoped budget (``MESH_RESTART_LIMIT`` per
  ``MESH_RESTART_WINDOW_SECS`` — a flapping worker retires permanently
  instead of storming).  The restarted worker cold-starts from the
  checkpoint store, is re-adopted onto the fleet's CURRENT params step
  (including a rollover that happened while it was down) before its
  puller touches the queue, and capacity returns without operator
  action.

**Fleet observability (OBSERVABILITY.md "Fleet observability").**  A
worker replica's spans, metrics, and HBM ledger live in its own
process; the wire carries them home: dispatch frames ship per-member
trace contexts and workers backhaul finished span records (result
frames + heartbeats) for ``adopt_spans`` stitching under a
per-incarnation clock-offset estimate; heartbeats are the typed
schema-versioned ``transport.Heartbeat`` carrying the worker's
registry snapshot + ledger rollup for the replica-labeled fleet merge;
and ``serving/slo.py`` watches the fleet completion stream against
``SERVING_SLO_*`` burn-rate targets, alarming into the flight
recorder.

Measured gates: ``benchmarks/bench_mesh.py`` (open-loop load at fixed
offered rate; p99 / shed rate / per-replica fill at 1/2/4 replicas)
and ``scripts/mesh_soak.py`` (chaos soak: paced load + periodic
``kill_worker``/``drop_heartbeat`` faults; zero lost admitted
requests, zero post-warmup compiles, bounded p99, zero unstitched
trace trees).
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.data.reader import (EstimatorAction,
                                      PathContextReader,
                                      canonicalize_contexts)
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving import engine as engine_lib
from code2vec_tpu.serving import memo as memo_lib
from code2vec_tpu.serving import slo as slo_lib
from code2vec_tpu.serving import transport as transport_lib
from code2vec_tpu.serving.engine import (ServingEngine, _Request,
                                         _resolve)
from code2vec_tpu.serving.errors import (AdoptionRejected,
                                         DeadlineExceeded, EngineClosed,
                                         EngineOverloaded, ReplicaDead,
                                         WireError)
from code2vec_tpu.serving.frontqueue import FrontQueue
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry import tracing as tracing_lib
from code2vec_tpu.telemetry.core import Counter, Gauge
from code2vec_tpu.training.trainer import PREDICT_TIERS

#: replica dispatch-breaker states (mirrors the extractor breaker's
#: numbering: serving/breaker_state semantics)
_BREAKER_CLOSED = 0
_BREAKER_HALF_OPEN = 1
_BREAKER_OPEN = 2


class _ReplicaSlot:
    """One row of the mesh replica table: transport + health + the
    dispatch accounting the weighting decisions read.  All mutable
    fields are guarded by the MESH's ``_cond`` lock (the replica's
    puller, the decode-completion hook, liveness monitor, supervisor,
    rollover, and retirement all touch them).

    ``dead`` is the liveness verdict (worker exited, wire corrupted,
    or heartbeats missed): a dead slot stops pulling and waits for the
    supervisor, which either restarts it (``transport`` is replaced —
    the OLD transport object doubles as the incarnation token crash-
    safe redispatch excludes) or retires it permanently once the
    window-scoped restart budget is spent."""

    __slots__ = ('rid', 'transport', 'thread', 'retired',
                 'retired_reason', 'adopted', 'device_indices',
                 'inflight', 'rows_dispatched', 'batches',
                 'breaker_fails', 'breaker_state', 'breaker_open_until',
                 'canarying', 'dead', 'restarting', 'restart_times',
                 'restarts')

    def __init__(self, rid: str, transport):
        self.rid = rid
        self.transport = transport
        self.thread: Optional[threading.Thread] = None
        self.retired = False
        #: why this slot retired ('restart_budget' | 'drain' |
        #: 'autoscale' | 'adopted_worker_exit'): an autoscaler
        #: post-mortem must tell budget-retire from drain
        self.retired_reason: Optional[str] = None
        #: externally-spawned worker the mesh adopted: its restart
        #: supervision belongs to the ORCHESTRATOR that spawned it —
        #: its death retires the slot instead of charging the local
        #: restart budget (SERVING.md "Elastic fleet")
        self.adopted = False
        #: this replica's device slice (indices into jax.devices())
        #: under MESH_DEVICES_PER_REPLICA placement; None when
        #: placement is off (every replica time-shares the host)
        self.device_indices: Optional[List[int]] = None
        self.inflight = 0
        self.rows_dispatched = 0
        self.batches = 0
        self.breaker_fails = 0
        self.breaker_state = _BREAKER_CLOSED
        self.breaker_open_until = 0.0
        self.canarying = False
        self.dead = False
        self.restarting = False
        self.restart_times: collections.deque = collections.deque()
        self.restarts = 0


class _ThreadReplica:
    """In-process replica transport: a ``ServingEngine`` in
    external-dispatch mode, called directly."""

    mode = 'thread'

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def dispatch(self, tier: str, taken: List[_Request],
                 rows: int) -> None:
        self.engine.dispatch_external(tier, taken, rows)

    def wait_ready(self) -> None:
        pass  # in-process: constructed ready

    def warmup(self) -> None:
        self.engine.warmup()

    def load_params(self, source, canary_batches: int,
                    min_agreement: float) -> Future:
        return self.engine.load_params(source,
                                       canary_batches=canary_batches,
                                       min_agreement=min_agreement)

    def adopt(self, params, source, step: Optional[int]) -> None:
        # in-process fleet swap: the canary replica's validated pytree
        # IS the candidate — pointer swap, no restore, no new ledger
        # entry (the arrays are shared across replicas)
        self.engine.adopt_params(params, step=step)

    def stats(self) -> Dict[str, object]:
        return self.engine.stats()

    def close(self) -> None:
        self.engine.close()


class _WorkerReplica:
    """Worker replica transport: a spawned process hosting its own
    model + engine, fed tokenized ``Batch`` payloads over the framed
    wire (serving/transport.py) and returning decoded results.  The
    carrier is a pipe (``mode='process'``) or TCP (``mode='socket'`` —
    the worker dials the mesh listener and introduces itself, the
    shape that lets replicas live on other machines).

    The parent-side receiver thread resolves in-flight dispatches and
    feeds the mesh's completion hook; the worker serves dispatches
    sequentially (its engine still decodes on its own pool) and
    heartbeats on its own thread, so a dispatch-busy worker still
    proves liveness.  A worker death — EOF, a corrupt frame, or a
    liveness kill — is reported ONCE through ``on_worker_dead`` with
    the in-flight batches attached, so the mesh can redispatch them
    instead of failing callers."""

    # the pending map and the send side of the wire are shared by the
    # puller, the receiver thread, the heartbeat monitor, and control
    # calls (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard _WorkerReplica._pending,_control,_seq by _lock
    def __init__(self, rid: str, mode: str,
                 config_overrides: Dict[str, object],
                 on_batch_done, log, on_worker_dead=None,
                 on_telemetry=None, on_spans=None,
                 listener: Optional[transport_lib.SocketListener] = None,
                 start_timeout_s: float = 600.0,
                 channel: Optional[object] = None):
        import multiprocessing
        self.rid = rid
        self.mode = mode
        self.log = log
        self._on_batch_done = on_batch_done
        self._on_worker_dead = on_worker_dead
        #: fleet-merge hook: (transport, registry snapshot, ledger
        #: rollup) per heartbeat — the mesh labels and merges
        self._on_telemetry = on_telemetry
        #: stitching accounting hook: (spans adopted, spans dropped)
        self._on_spans = on_spans
        self._start_timeout_s = start_timeout_s
        self._listener = listener
        self._cancel = threading.Event()
        #: stamped by the receiver on every frame (heartbeats included);
        #: the mesh liveness monitor reads it
        self.last_heartbeat = time.perf_counter()
        #: the worker's last self-reported {'inflight'} (surfaced as
        #: ``worker_reported_inflight`` in mesh.stats())
        self.heartbeat_info: Dict[str, object] = {}
        #: this incarnation's monotonic-clock offset estimate (min-
        #: filter over the ready handshake + every heartbeat) — remote
        #: span stamps shift by it at adoption, so cross-host stamps
        #: order correctly in the stitched tree
        self.clock = transport_lib.ClockOffset()
        #: the worker's last memory-ledger rollup ({attributed_bytes,
        #: budget_bytes, buckets}) — mesh.stats()'s per-worker HBM view
        self.ledger_info: Dict[str, object] = {}
        #: receiver-thread-only: last merged counter values, for the
        #: delta-inc fleet merge (fresh per incarnation, so counters
        #: accumulate across restarts)
        self._merge_last: Dict[str, float] = {}
        #: the ready handshake's {'params_step', 'capabilities'}
        self.ready_info: Dict[str, object] = {}
        ctx = multiprocessing.get_context('spawn')
        if channel is not None:
            # ADOPTED worker (SERVING.md "Elastic fleet"): an external
            # orchestrator exec'd scripts/mesh_worker.py against the
            # mesh listener and this dial-in arrived with an
            # unexpected rid.  There is no local process to spawn,
            # join, or supervise — restart supervision for adopted
            # workers is the orchestrator's job; a later death just
            # retires the slot.
            self._proc = None
            self._channel = channel
        elif mode == 'socket':
            address = listener.address
            self._channel = None  # claimed from the listener at ready
            self._proc = ctx.Process(
                target=_replica_worker_main,
                args=(rid, config_overrides, None, address), daemon=True)
            self._proc.start()
        else:
            self._conn, child = ctx.Pipe()
            self._proc = ctx.Process(
                target=_replica_worker_main,
                args=(rid, config_overrides, child, None), daemon=True)
            # spawn only: the worker's cold start (model build + warmup)
            # is the expensive part, and N replicas must pay it
            # CONCURRENTLY — the mesh constructs every transport first,
            # then wait_ready()s each, so fleet startup is ~one worker's
            # wall clock, not N of them
            self._proc.start()
            child.close()
            self._channel = transport_lib.PipeTransport(self._conn)
        self._lock = threading.Lock()
        self._pending: Dict[int, Tuple[List[_Request], int]] = {}
        self._seq = 0
        self._control: Dict[int, Future] = {}
        self._receiver: Optional[threading.Thread] = None

    def _reap_on_start_failure(self) -> None:
        """Failed-startup cleanup: a SPAWNED worker is reaped (process
        + channel); an ADOPTED one has no local process and its channel
        must stay open — the adoption path still owes the dial-in a
        typed ``adopt_rejected`` frame before the close."""
        if self._proc is not None:
            self.reap()

    def wait_ready(self) -> None:
        """Block until the worker reported ready, then start the
        receiver.  Must run before the first dispatch/control call.
        Interruptible via ``cancel()`` (a mesh closing mid-restart must
        not wait out a worker cold start)."""
        if self._receiver is not None:
            return
        deadline = time.perf_counter() + self._start_timeout_s
        if self._channel is None:
            # socket mode: the worker dials in; claim its validated
            # hello from the listener, pinned to THIS incarnation's
            # pid (a reaped predecessor's late hello must not be
            # handed to the restart)
            try:
                self._channel, _hello = self._listener.claim(
                    self.rid, self._start_timeout_s, cancel=self._cancel,
                    pid=self._proc.pid)
            except BaseException as exc:
                self._reap_on_start_failure()
                raise RuntimeError(
                    'mesh replica %s worker never dialed in: %r'
                    % (self.rid, exc))
        while not self._channel.poll(0.25):
            if self._cancel.is_set():
                self._reap_on_start_failure()
                raise RuntimeError('mesh replica %s startup cancelled '
                                   '(mesh closing)' % self.rid)
            if time.perf_counter() >= deadline:
                self._reap_on_start_failure()
                raise RuntimeError(
                    'mesh replica %s worker did not come up within %.0fs'
                    % (self.rid, self._start_timeout_s))
        try:
            msg = self._channel.recv()
        except (EOFError, OSError, WireError) as exc:
            # worker died before it could even report its failure
            self._reap_on_start_failure()
            raise RuntimeError(
                'mesh replica %s worker exited during startup (%r) — '
                'check the worker log; worker replicas need a '
                'checkpointed model with a retained step'
                % (self.rid, exc))
        if msg[0] == 'failed':
            self._reap_on_start_failure()
            raise RuntimeError('mesh replica %s worker failed to '
                               'start: %s' % (self.rid, msg[1]))
        if msg[0] != 'ready':
            self._reap_on_start_failure()
            raise RuntimeError('mesh replica %s worker failed to start: '
                               '%r' % (self.rid, msg))
        self.ready_info = msg[1] if len(msg) > 1 and \
            isinstance(msg[1], dict) else {}
        self.last_heartbeat = time.perf_counter()
        # first clock-offset sample: the ready frame carries the
        # worker's monotonic stamp (heartbeats refresh it from here on)
        self.clock.observe(self.ready_info.get('t_mono'),
                           self.last_heartbeat)
        self._receiver = threading.Thread(target=self._recv_loop,
                                          daemon=True,
                                          name='mesh-recv-%s' % self.rid)
        self._receiver.start()

    def _control_call(self, kind: str, *payload,
                      timeout: Optional[float] = 600.0):
        future: Future = Future()
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._control[seq] = future
            self._channel.send((kind, seq) + payload)
        return future.result(timeout)

    def dispatch(self, tier: str, taken: List[_Request],
                 rows: int) -> None:
        batches = [request.batch for request in taken]
        # per-member trace context: the worker runs its engine spans
        # UNDER the parent's trace and ships them back for stitching
        # (None for untraced members — the worker records nothing).
        # Re-parenting happens PARENT-side at adoption (the member's
        # span_parent object), so the context stays minimal.
        # the scenario tag rides the dispatch trace context so the
        # worker-side envelope stays attributable per workload after
        # stitching (WORKLOADS.md; root attrs stamped at submit)
        ctxs = [None if request.trace is None else
                {'trace_id': request.trace.trace_id,
                 'sampled': request.trace.sampled,
                 'scenario': (request.trace.root.attrs
                              or {}).get('scenario')}
                for request in taken]
        seq = None
        try:
            with self._lock:
                seq = self._seq
                self._seq += 1
                self._pending[seq] = (taken, rows)
                self._channel.send(('dispatch', seq, tier, batches,
                                    ctxs))
        except BaseException as exc:
            entry = None
            if seq is not None:
                with self._lock:
                    entry = self._pending.pop(seq, None)
            # a dead wire at send time is a worker death with this batch
            # in flight: hand the members to the mesh's crash-safe
            # redispatch (first crash re-admits them at the queue front;
            # a second fails them typed), then re-raise so the puller's
            # breaker accounts the replica failure.  The receiver's EOF
            # path may race this — whoever pops the pending entry owns
            # the requests, so they are handled exactly once.
            if entry is not None and self._on_worker_dead is not None:
                try:
                    self._on_worker_dead(
                        self, [entry],
                        WireError('mesh replica %s wire send failed: %r'
                                  % (self.rid, exc)))
                except Exception:
                    for request in entry[0]:
                        request.fail(EngineClosed(
                            'mesh replica %s wire send failed: %r'
                            % (self.rid, exc)))
            raise
        # the worker pops its queue-wait here, not in an engine this
        # process can see: close the span at hand-off so queue time is
        # attributed, not smeared into the trace tail
        now = time.perf_counter()
        for request in taken:
            if request.queue_span is not None:
                request.trace.end(request.queue_span, now)
                request.queue_span = None

    def _recv_loop(self) -> None:
        while True:
            try:
                msg = self._channel.recv()
                # a partitioned network loses frames while both
                # endpoints stay up: results AND heartbeats vanish, so
                # the liveness monitor (not the breaker) is what
                # notices
                if faults.maybe_fire('partition'):
                    continue
                if msg[0] == 'heartbeat':
                    # schema-versioned typed payload: version skew
                    # between a worker and its mesh fails the replica
                    # TYPED through the one death path below, instead
                    # of feeding the telemetry merge a guessed pickle
                    # shape
                    transport_lib.check_heartbeat(msg[2])
            except (EOFError, OSError, WireError) as exc:
                # worker died (EOF) or its stream is poisoned (a partial
                # frame from a mid-write death fails TYPED instead of
                # misparsing every later frame): drain the in-flight
                # state once and report the death upward — the mesh
                # redispatches the batches and the supervisor restarts
                # the worker
                with self._lock:
                    pending = list(self._pending.values())
                    self._pending.clear()
                    control = list(self._control.values())
                    self._control.clear()
                dead = ReplicaDead(
                    'mesh replica %s worker died (%r) with %d '
                    'dispatch(es) in flight'
                    % (self.rid, exc, len(pending)))
                for future in control:
                    if not future.done():
                        future.set_exception(dead)
                if self._on_worker_dead is not None:
                    try:
                        self._on_worker_dead(self, pending, dead)
                    except Exception:
                        for taken, _rows in pending:
                            for request in taken:
                                request.fail(dead)
                else:
                    for taken, _rows in pending:
                        for request in taken:
                            request.fail(dead)
                return
            self.last_heartbeat = time.perf_counter()
            kind, seq = msg[0], msg[1]
            if kind == 'heartbeat':
                beat = msg[2]
                self.clock.observe(beat.t_mono, self.last_heartbeat)
                self.heartbeat_info = {'inflight': beat.inflight}
                if beat.ledger:
                    self.ledger_info = beat.ledger
                # spans orphaned from their result frame — finished
                # late, or about to be orphaned by a crash — ride the
                # beat and stitch while their dispatch is still pending
                self._adopt_pending_bundles(beat.spans)
                if beat.telemetry is not None and \
                        self._on_telemetry is not None:
                    try:
                        self._on_telemetry(self, beat.telemetry,
                                           beat.ledger)
                    except Exception:
                        pass  # the merge must never kill the receiver
                continue
            if kind in ('result', 'error'):
                with self._lock:
                    entry = self._pending.pop(seq, None)
                    ctrl = self._control.pop(seq, None)
                if entry is not None:
                    taken, rows = entry
                    if kind == 'result':
                        # graft the worker-side span records into the
                        # live traces BEFORE delivery finishes them —
                        # a finished trace is already serialized and
                        # cannot be stitched
                        self._adopt_member_bundles(
                            seq, taken, msg[3] if len(msg) > 3 else None)
                        for request, results in zip(taken, msg[2]):
                            request.deliver(results)
                            request.finish_trace()
                        self._on_batch_done(self, rows, taken, True)
                    else:
                        for request in taken:
                            request.fail(msg[2])
                        self._on_batch_done(self, rows, taken, False)
                elif ctrl is not None:
                    if kind == 'result':
                        _resolve(ctrl, msg[2])
                    elif not ctrl.done():
                        ctrl.set_exception(msg[2])
            elif kind == 'closed':
                with self._lock:
                    ctrl = self._control.pop(seq, None)
                if ctrl is not None:
                    _resolve(ctrl, None)
                return

    # ------------------------------------------------ trace stitching
    def _adopt_one(self, request: Optional[_Request],
                   spans: List[dict]) -> Tuple[int, int]:
        """Graft one bundle's records into its member's live trace;
        returns (adopted, dropped)."""
        if request is None or request.trace is None:
            return 0, len(spans)
        adopted = request.trace.adopt_spans(
            spans, self.clock.offset, parent=request.span_parent)
        return adopted, len(spans) - adopted

    def _adopt_member_bundles(self, seq: int, taken: List[_Request],
                              bundles) -> None:
        """Result-frame stitching: the worker's ``sink.collect(seq)``
        guarantees every bundle here belongs to THIS dispatch, so
        bundles align with its members by index (``seq`` double-checks
        the contract — a mismatch is dropped and counted, never
        mis-grafted; late bundles from other dispatches only ever
        travel on heartbeats)."""
        if not bundles:
            return
        adopted = dropped = 0
        for bundle in bundles:
            member = bundle.get('member')
            request = (taken[member]
                       if bundle.get('seq') == seq
                       and isinstance(member, int)
                       and 0 <= member < len(taken) else None)
            got, lost = self._adopt_one(request,
                                        bundle.get('spans') or [])
            adopted += got
            dropped += lost
        if (adopted or dropped) and self._on_spans is not None:
            self._on_spans(adopted, dropped)

    def _adopt_pending_bundles(self, bundles) -> None:
        """Heartbeat-ridden stitching: each bundle names its dispatch
        seq; bundles whose dispatch already concluded (their trace is
        finished and written) are counted dropped, not mis-grafted."""
        if not bundles:
            return
        adopted = dropped = 0
        for bundle in bundles:
            with self._lock:
                entry = self._pending.get(bundle.get('seq'))
            request = None
            if entry is not None:
                member = bundle.get('member')
                taken = entry[0]
                if isinstance(member, int) and 0 <= member < len(taken):
                    request = taken[member]
            got, lost = self._adopt_one(request,
                                        bundle.get('spans') or [])
            adopted += got
            dropped += lost
        if (adopted or dropped) and self._on_spans is not None:
            self._on_spans(adopted, dropped)

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def cancel(self) -> None:
        """Abort a wait_ready in flight (mesh closing mid-restart)."""
        self._cancel.set()
        self.kill()

    def kill(self) -> None:
        """Hard-stop a hung or partitioned worker: SIGKILL + close the
        channel so the blocked receiver unblocks with EOF and the death
        path runs there exactly once."""
        try:
            if self._proc is not None and self._proc.is_alive():
                self._proc.kill()
        except Exception:
            pass
        try:
            if self._channel is not None:
                self._channel.close()
        except Exception:
            pass

    def reap(self) -> None:
        """Terminate + join a worker that is already dead or being
        abandoned, without the graceful close handshake."""
        self.kill()
        try:
            if self._proc is not None:
                self._proc.join(timeout=30.0)
        except Exception:
            pass

    def warmup(self) -> None:
        pass  # the worker warms before it reports ready

    def load_params(self, source, canary_batches: int,
                    min_agreement: float) -> Future:
        """Arm a canaried rollover IN the worker; the returned future
        resolves with the report (a parent-side waiter polls — the
        canary concludes on the worker's live dispatch traffic)."""
        if not isinstance(source, (int, str)) or isinstance(source, bool):
            raise RuntimeError(
                'worker-mode replicas roll over from checkpoint refs '
                '(step int or model path), not param pytrees — pytrees '
                'do not cross process (or host) boundaries')
        self._control_call('load_params', source, canary_batches,
                           min_agreement)
        handle: Future = Future()

        def wait() -> None:
            try:
                while True:
                    report = self._control_call('poll_rollover')
                    if report is not None:
                        _resolve(handle, report)
                        return
                    time.sleep(0.05)
            except BaseException as exc:
                if not handle.done():
                    handle.set_exception(exc)

        threading.Thread(target=wait, daemon=True,
                         name='mesh-canary-%s' % self.rid).start()
        return handle

    def adopt(self, params, source, step: Optional[int]) -> None:
        # cross-process fleet swap ships the checkpoint REF: the worker
        # restores it against its own abstract targets (canary already
        # validated the content on live traffic; canary_batches=0 swaps
        # without re-canarying)
        del params  # unused: pytrees do not cross the process wire
        self._control_call('load_params', source, 0, 0.0)
        while self._control_call('poll_rollover') is None:
            time.sleep(0.02)

    def stats(self) -> Dict[str, object]:
        return self._control_call('stats')

    def close(self) -> None:
        if self._receiver is None:
            # never became ready (a sibling's startup failed, or a
            # cancelled restart): nothing to hand-shake with — just
            # reap the worker
            self.reap()
            return
        try:
            self._control_call('close', timeout=60.0)
        except BaseException:
            pass  # a dead worker's wire refuses the handshake: reap it
        if self._receiver is not threading.current_thread():
            # the worker-dead path closes from the receiver itself
            self._receiver.join(timeout=30.0)
        if self._proc is not None:
            self._proc.join(timeout=60.0)
            if self._proc.is_alive():
                self._proc.terminate()
        if self._channel is not None:
            self._channel.close()


def _worker_ledger_rollup() -> Dict[str, object]:
    """Compact memory-ledger view for the heartbeat: enough for the
    mesh's per-worker HBM rollup (budget pressure visible BEFORE the
    remote worker OOMs), small enough to ride every beat."""
    from code2vec_tpu.telemetry import memory as memory_lib
    ledger = memory_lib.ledger()
    return {'attributed_bytes': ledger.attributed_bytes(),
            'budget_bytes': ledger.budget_bytes(),
            'buckets': {bucket: ledger.bucket_bytes(bucket)
                        for bucket in memory_lib.BUCKETS}}


def _replica_worker_main(rid: str, config_overrides: Dict[str, object],
                         conn, address) -> None:
    """Worker replica entry point (spawned): build the model from the
    shipped config, host one external-dispatch engine, serve the
    framed wire — a pipe connection (``conn``) in process mode, or a
    TCP dial to the mesh listener (``address``) in socket mode.  The
    protocol is identical either way."""
    import os
    import signal
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    if conn is not None:
        channel = transport_lib.PipeTransport(conn)
    else:
        channel = transport_lib.dial(address, rid, os.getpid())
    # the heartbeat thread and the serve loop share the send side
    send_lock = threading.Lock()

    def send(message) -> None:
        with send_lock:
            channel.send(message)

    try:
        config = Config(**config_overrides)
        if config.MESH_TELEMETRY_BACKHAUL == 1:
            # the parent resolved the backhaul decision at spawn: with
            # it on, this worker's registry snapshots + ledger rollup
            # ride every heartbeat into the replica-labeled fleet merge
            from code2vec_tpu.telemetry.jit_tracker import \
                install_compile_listener
            tele_core.enable()
            install_compile_listener()
        model = Code2VecModel(config)
        engine = ServingEngine(
            config, model.trainer, model.params, model.vocabs,
            decode_table=model._target_index_to_word,
            tiers=config.serving_warm_tiers,
            param_source=model._serving_param_source(),
            replica_id=rid, external_dispatch=True, log=config.log)
        engine.warmup()
    except BaseException as exc:
        # the parent must learn WHY this replica died, not just see an
        # EOF on the wire (a missing retained step, a model-build
        # failure, ...)
        try:
            send(('failed', repr(exc)))
        except BaseException:
            pass
        raise
    rollover: Dict[str, object] = {'handle': None}
    inflight = [0]
    stop_beats = threading.Event()
    # worker-side half of cross-process stitching: member traces run
    # under the parent's shipped contexts and their finished span
    # records backhaul on the result frame (or a heartbeat)
    sink = tracing_lib.RemoteSpanSink(rid)

    def beat_loop() -> None:
        """Liveness, decoupled from dispatch: a dispatch-busy worker
        still beats; a hung or drilled one goes silent and the mesh
        liveness monitor — not the breaker — declares it dead.  The
        typed payload also carries the observability backhaul: span
        records not yet shipped on a result frame, the telemetry
        registry snapshot, and the memory-ledger rollup."""
        period = float(config.MESH_HEARTBEAT_SECS)
        if period <= 0:
            return
        while not stop_beats.wait(period):
            if faults.maybe_fire('drop_heartbeat'):
                continue  # the drilled shape of a hung worker
            backhaul = config.MESH_TELEMETRY_BACKHAUL == 1
            try:
                # the whole backhaul honors the off switch: with it
                # off, beats carry liveness + the clock stamp only
                telemetry = (tele_core.registry().snapshot()
                             if backhaul and tele_core.enabled()
                             else None)
                ledger = _worker_ledger_rollup() if backhaul else None
            except Exception:
                telemetry, ledger = None, None
            try:
                send(('heartbeat', -1, transport_lib.Heartbeat(
                    inflight=inflight[0],
                    t_mono=time.perf_counter(),
                    # age-gated: a just-finished bundle belongs to its
                    # own result frame; one still here after ~a beat
                    # has missed it (stall or crash-in-progress) and
                    # ships now
                    spans=sink.drain(min_age_s=period / 2),
                    telemetry=telemetry,
                    ledger=ledger)))
            except BaseException:
                return  # wire gone: the serve loop is exiting too

    if faults.maybe_fire('adopt_stall'):
        # the drilled shape of a worker wedging between dial-in and
        # ready: the mesh's bounded adoption wait (or startup timeout)
        # must drop it typed instead of hanging the adoption thread
        time.sleep(faults.ADOPT_STALL_SECONDS)
    engine_stats = engine.stats()
    send(('ready', {
        'params_step': engine_stats.get('params_step'),
        't_mono': time.perf_counter(),
        # 'devices' is the placement view: under MESH_DEVICE_INDICES
        # this worker's sub-mesh covers exactly its slice, and the
        # mesh's stats/assertions read the slice from here
        'capabilities': {'tiers': list(config.serving_warm_tiers),
                         'wire': config.BATCH_WIRE_FORMAT,
                         'proto': transport_lib.WIRE_PROTO,
                         'devices': [int(d.id) for d in
                                     model.mesh.devices.flatten()]},
    }))
    beats = threading.Thread(target=beat_loop, daemon=True,
                             name='mesh-beat-%s' % rid)
    beats.start()
    try:
        while True:
            msg = channel.recv()
            kind, seq = msg[0], msg[1]
            try:
                if kind == 'dispatch':
                    if faults.maybe_fire('kill_worker'):
                        # mid-batch SIGKILL: the parent has this
                        # dispatch in _pending, so the drill exercises
                        # exactly the crash-safe redispatch path
                        os.kill(os.getpid(), signal.SIGKILL)
                    tier, batches = msg[2], msg[3]
                    ctxs = (msg[4] if len(msg) > 4
                            else [None] * len(batches))
                    requests = []
                    for member, (batch, ctx) in enumerate(
                            zip(batches, ctxs)):
                        trace = (sink.begin('serving.remote', ctx, seq,
                                            member)
                                 if ctx is not None else None)
                        requests.append(_Request(batch, tier,
                                                 future=Future(),
                                                 trace=trace))
                    rows = sum(request.rows for request in requests)
                    inflight[0] += 1
                    try:
                        engine.dispatch_external(tier, requests, rows)
                        results = [request.future.result(timeout=600)
                                   for request in requests]
                    finally:
                        inflight[0] -= 1
                    # member traces finish on the decode threads right
                    # after the futures resolve; wait them out so the
                    # result frame carries the full bundle set (a late
                    # finisher rides the next heartbeat instead)
                    sink.wait_finished([r.trace for r in requests],
                                       timeout=5.0)
                    if faults.maybe_fire('kill_worker_after_execute'):
                        # die AFTER the device work but BEFORE the
                        # result frame: the finished spans ride a
                        # heartbeat (the beat thread drains the sink),
                        # then the SIGKILL orphans the batch — the
                        # stitched-trace drill's way of proving a
                        # redispatched request shows BOTH incarnations'
                        # device work
                        time.sleep(max(0.5,
                                       3 * config.MESH_HEARTBEAT_SECS))
                        os.kill(os.getpid(), signal.SIGKILL)
                    send(('result', seq, results, sink.collect(seq)))
                elif kind == 'load_params':
                    source, n_canary, floor = msg[2], msg[3], msg[4]
                    rollover['handle'] = engine.load_params(
                        source, canary_batches=n_canary,
                        min_agreement=floor)
                    send(('result', seq, True))
                elif kind == 'poll_rollover':
                    handle = rollover['handle']
                    if handle is not None and handle.done():
                        rollover['handle'] = None
                        send(('result', seq, handle.result()))
                    else:
                        send(('result', seq, None))
                elif kind == 'stats':
                    send(('result', seq, engine.stats()))
                elif kind == 'close':
                    engine.close()
                    send(('closed', seq))
                    return
                else:
                    raise RuntimeError('unknown mesh wire message %r'
                                       % (kind,))
            except BaseException as exc:
                try:
                    send(('error', seq, exc))
                except BaseException:
                    send(('error', seq, RuntimeError(repr(exc))))
    finally:
        stop_beats.set()
        engine.close()


# ----------------------------------------------------------------- mesh
class ServingMesh:
    """N serving replicas, one shared front queue.  Build via
    ``Code2VecModel.serving_mesh()``; the API mirrors the single
    engine's (``submit`` / ``predict`` / ``submit_neighbors`` /
    ``load_params`` / ``follow_checkpoints`` / ``close``)."""

    # the replica table, fleet service window, rollover slot, restart
    # hand-off and close flags are shared by submitters, N pullers,
    # decode-completion hooks, the supervisor, the liveness monitor,
    # and control calls (lock-discipline rule, ANALYSIS.md); _cond
    # wraps _lock:
    # graftlint: guard ServingMesh._closed,_drain,_rollover,_index_rollover,_index_version,_params_step,_rows_total,_service_window,_service_window_rows,_service_rows_per_s,_restart_pending,_next_rid by _lock|_cond
    def __init__(self, model, replicas: Optional[int] = None,
                 tiers: Optional[Sequence[str]] = None,
                 mode: Optional[str] = None,
                 max_delay_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 queue_bound: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_secs: Optional[float] = None,
                 canary_batches: Optional[int] = None,
                 canary_agreement: Optional[float] = None,
                 params_step: Optional[int] = None,
                 memo_cache_bytes: Optional[int] = None,
                 memo_semantic_epsilon: Optional[float] = None,
                 heartbeat_secs: Optional[float] = None,
                 heartbeat_misses: Optional[int] = None,
                 restart_limit: Optional[int] = None,
                 restart_window_secs: Optional[float] = None,
                 restart_backoff_secs: Optional[float] = None,
                 tracer: Optional[tracing_lib.Tracer] = None,
                 tracing_sample_rate: Optional[float] = None,
                 log=None):
        config = model.config
        self.config = config
        self.log = log if log is not None else config.log
        n = int(replicas if replicas is not None else config.MESH_REPLICAS)
        if n < 1:
            raise ValueError('a mesh needs >= 1 replica, got %d' % n)
        self.mode = mode if mode is not None else config.MESH_REPLICA_MODE
        if self.mode not in ('thread', 'process', 'socket'):
            raise ValueError("MESH_REPLICA_MODE must be 'thread', "
                             "'process' or 'socket', got %r"
                             % (self.mode,))
        # ---- self-healing knobs (SERVING.md "Multi-host mesh") ----
        self.heartbeat_secs = float(
            heartbeat_secs if heartbeat_secs is not None
            else config.MESH_HEARTBEAT_SECS)
        self.heartbeat_misses = max(1, int(
            heartbeat_misses if heartbeat_misses is not None
            else config.MESH_HEARTBEAT_MISSES))
        self.restart_limit = max(0, int(
            restart_limit if restart_limit is not None
            else config.MESH_RESTART_LIMIT))
        self.restart_window_s = float(
            restart_window_secs if restart_window_secs is not None
            else config.MESH_RESTART_WINDOW_SECS)
        self.restart_backoff_s = float(
            restart_backoff_secs if restart_backoff_secs is not None
            else config.MESH_RESTART_BACKOFF_SECS)
        tiers = tuple(tiers if tiers is not None
                      else config.serving_warm_tiers)
        for tier in tiers:
            if tier not in PREDICT_TIERS:
                raise ValueError('unknown tier %r; expected a subset of '
                                 '%s' % (tier, PREDICT_TIERS))
        self.tiers = tiers
        self.max_delay_s = (max_delay_ms if max_delay_ms is not None
                            else config.SERVING_MAX_DELAY_MS) / 1e3
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else config.SERVING_DEADLINE_MS)
        self.deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None
        self.max_inflight = max(1, int(
            max_inflight if max_inflight is not None
            else config.MESH_MAX_INFLIGHT))
        self.breaker_threshold = max(1, int(
            breaker_threshold if breaker_threshold is not None
            else config.MESH_BREAKER_THRESHOLD))
        self.breaker_cooldown_s = float(
            breaker_cooldown_secs if breaker_cooldown_secs is not None
            else config.MESH_BREAKER_COOLDOWN_SECS)
        self.canary_batches = (canary_batches
                               if canary_batches is not None
                               else config.SERVING_CANARY_BATCHES)
        self.canary_agreement = (canary_agreement
                                 if canary_agreement is not None
                                 else config.SERVING_CANARY_AGREEMENT)
        # submit-side tokenizer + ladder geometry (identical to every
        # replica's: same config, same mesh data axis — which is what
        # makes admitted results bit-identical to a single engine's)
        self._reader = PathContextReader(model.vocabs, config,
                                         EstimatorAction.Predict)
        # ---- per-replica device placement (SERVING.md "Elastic
        # fleet") ----  MESH_DEVICES_PER_REPLICA partitions
        # jax.devices() into disjoint contiguous slices; each worker
        # builds its own sub-mesh over its slice, so N replicas on one
        # host stop contending for the same chips.
        self.devices_per_replica = max(
            0, int(config.MESH_DEVICES_PER_REPLICA))
        self._placement: Optional[List[List[int]]] = None
        if self.devices_per_replica > 0:
            if self.mode == 'thread':
                raise ValueError(
                    'MESH_DEVICES_PER_REPLICA needs a worker mode '
                    "(MESH_REPLICA_MODE 'process' or 'socket'): thread "
                    "replicas dispatch through the parent trainer's "
                    'programs, which are compiled over the FULL parent '
                    'mesh and cannot be re-placed per replica')
            # carve enough slices for the autoscaler's ceiling, not
            # just the build-time fleet: scale-up must never fail on
            # placement the mesh could have reserved up front
            n_slices = n
            if config.AUTOSCALE_MAX_REPLICAS > 0:
                n_slices = max(n, int(config.AUTOSCALE_MAX_REPLICAS))
            self._placement = mesh_lib.partition_device_indices(
                n_slices, self.devices_per_replica)
        if self._placement is not None:
            # placement on: the submit-side geometry follows a SLICE's
            # data axis, not the parent mesh's — a parent-ladder top
            # bucket wider than the slice ladder's would tokenize
            # batches no replica has a warm program for
            self.data_axis = (self.devices_per_replica
                              // max(1, int(config.MESH_MODEL_AXIS_SIZE)))
        else:
            self.data_axis = model.mesh.shape[mesh_lib.DATA_AXIS]
        self.buckets = engine_lib.batch_ladder(
            config.serving_batch_buckets, self.data_axis)
        bound = (queue_bound if queue_bound is not None
                 else config.MESH_QUEUE_BOUND)
        # auto bound scales WITH the fleet: every replica adds its share
        # of absorbable backlog
        self.queue_bound: Optional[int] = (
            None if bound < 0 else
            n * 8 * self.buckets[-1] if bound == 0 else bound)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._drain = False
        self._rollover: Optional[Dict[str, object]] = None
        # index rollover (canaried index swap — the params-canary
        # machinery generalized to indexes): candidate + live-traffic
        # shadow-query agreement state, armed by rollover_index()
        self._index_rollover: Optional[Dict[str, object]] = None
        self._index_version = 0
        self._rows_total = 0
        # fleet service window: same estimator the engine runs, fed by
        # EVERY replica's completions — the fleet-wide drain rate
        self._service_rows_per_s = 0.0
        self._service_window: collections.deque = collections.deque()
        self._service_window_rows = 0
        if params_step is not None:
            self._params_step: Optional[int] = params_step
        elif model.state is not None:
            self._params_step = int(model.state.step)
        else:
            self._params_step = None
        self._param_source = model._serving_param_source()
        self._follow_thread: Optional[threading.Thread] = None
        self._follow_stop = threading.Event()
        # self-healing state: the close event interrupts supervisor
        # backoffs; _restart_pending is the transport a restart is
        # readying (close() cancels it so fail-fast close never waits
        # out — or leaks — a worker cold start)
        self._close_event = threading.Event()
        self._restart_pending: Optional[_WorkerReplica] = None
        self._supervisor: Optional[threading.Thread] = None
        self._liveness_thread: Optional[threading.Thread] = None
        self._listener: Optional[transport_lib.SocketListener] = None
        self._model_config_overrides: Optional[Dict[str, object]] = None
        # elastic fleet (SERVING.md "Elastic fleet"): scale-up needs
        # the model handle to build new replicas; adoption needs a
        # thread watching the listener for dial-ins the mesh never
        # spawned; rids stay unique across scale-downs and -ups
        self._model = model
        self._next_rid = n
        self._adopt_thread: Optional[threading.Thread] = None
        #: externally-owned workers' ready wait (dial-in -> ready
        #: frame): covers the dialed-in worker's cold start — it dials
        #: FIRST, then builds + warms (scripts/mesh_worker.py).  Drills
        #: shorten it to exercise adopt_stall.
        self.adopt_ready_timeout_s = 600.0
        self._autoscaler = None
        # instruments (mesh-level; per-replica series ride the engines'
        # replica-labeled mirrors)
        self.requests_total = Counter('mesh/requests_total')
        self.rollover_total = Counter('mesh/rollover_total')
        self.rollover_rollbacks_total = Counter(
            'mesh/rollover_rollbacks_total')
        self.index_rollover_total = Counter('index/rollovers_total')
        self.index_rollover_rollbacks_total = Counter(
            'index/rollover_rollbacks_total')
        self.index_rollover_agreement = Gauge(
            'index/rollover_agreement')
        self.breaker_open_total = Counter(
            'mesh/replica_breaker_open_total')
        self.replicas_gauge = Gauge('mesh/replicas')
        self.serving_gauge = Gauge('mesh/replicas_serving')
        self.live_gauge = Gauge('mesh/replicas_live')
        self.restarts_total = Counter('mesh/restarts_total')
        self.redispatched_total = Counter('mesh/redispatched_total')
        # elastic-fleet accounting: WHY replicas leave, and how many
        # external workers the mesh adopted vs turned away
        self.retired_total = Counter('mesh/retired_total')
        self.adopted_total = Counter('mesh/adopted_total')
        self.adoption_rejected_total = Counter(
            'mesh/adoption_rejected_total')
        self.heartbeat_misses_total = Counter(
            'mesh/heartbeat_misses_total')
        # fleet observability plane (OBSERVABILITY.md "Fleet
        # observability"): stitching + backhaul accounting
        self.adopted_spans_total = Counter('tracing/adopted_spans_total')
        self.remote_spans_dropped_total = Counter(
            'tracing/remote_spans_dropped_total')
        self.worker_snapshots_total = Counter(
            'mesh/worker_snapshots_total')
        # tracing: ONE tracer shared with every thread-mode replica, so
        # the flight recorder and span log see the whole fleet
        rate = (tracing_sample_rate if tracing_sample_rate is not None
                else config.tracing_sample_rate)
        # same ownership rule as the engine: an injected tracer is the
        # caller's to close
        self._owns_tracer = tracer is None
        if tracer is not None:
            self._tracer: Optional[tracing_lib.Tracer] = tracer
        elif rate > 0:
            out_dir = None
            if getattr(config, 'TELEMETRY_DIR', None) or \
                    config.is_saving or config.is_loading:
                from code2vec_tpu.telemetry.stepwatch import telemetry_dir
                out_dir = telemetry_dir(config)
            self._tracer = tracing_lib.Tracer(
                out_dir, sample_rate=rate,
                slow_ms=config.TRACING_SLOW_MS,
                flight_traces=config.TRACING_FLIGHT_TRACES,
                log=self.log)
        else:
            self._tracer = None
        # SLO burn-rate monitor (serving/slo.py): availability + p99
        # targets over the fleet's completion stream, alarming into the
        # shared flight recorder
        self._slo: Optional[slo_lib.SloMonitor] = None
        if config.SERVING_SLO_AVAILABILITY > 0 or \
                config.SERVING_SLO_P99_MS > 0:
            self._slo = slo_lib.SloMonitor(
                availability=config.SERVING_SLO_AVAILABILITY,
                p99_ms=config.SERVING_SLO_P99_MS,
                fast_window_s=config.SERVING_SLO_FAST_WINDOW_SECS,
                slow_window_s=config.SERVING_SLO_SLOW_WINDOW_SECS,
                burn_threshold=config.SERVING_SLO_BURN_THRESHOLD,
                tracer=self._tracer, log=self.log)
        self._queue = FrontQueue(tiers, self.queue_bound,
                                 fleet_rate=self._fleet_rate,
                                 log=self.log)
        self._index = None
        # scenario traffic plane (workloads/profile.py): optional
        # ProfileRecorder tapped at admission by submit/submit_neighbors/
        # submit_blended; armed via record_traffic(), never re-armed
        # concurrently with traffic in this codebase's use, so reads
        # need no lock (a racy None just skips one record)
        self._traffic_recorder = None
        self._aux_pool = ThreadPoolExecutor(max_workers=2,
                                            thread_name_prefix='mesh-aux')
        # memoization tier (serving/memo.py, SERVING.md "Memoization
        # tier"): checked at submit BEFORE tokenize/admit; built once
        # here and never reassigned, so reads need no lock
        memo_bytes = int(memo_cache_bytes if memo_cache_bytes is not None
                         else config.MEMO_CACHE_BYTES)
        epsilon = float(memo_semantic_epsilon
                        if memo_semantic_epsilon is not None
                        else config.MEMO_SEMANTIC_EPSILON)
        self._memo: Optional[memo_lib.MemoCache] = (
            memo_lib.MemoCache(memo_bytes, semantic_epsilon=epsilon,
                               params_step=self._params_step,
                               log=self.log)
            if memo_bytes > 0 else None)
        # ---- replica table ----
        self._replicas: List[_ReplicaSlot] = []
        try:
            if self.mode == 'socket':
                # workers dial in: the listener must be up before the
                # first spawn.  MESH_SOCKET_HOST is the bind address —
                # 127.0.0.1 keeps spawned-local workers loopback-only;
                # a routable address lets workers on other machines
                # dial the same wire.
                self._listener = transport_lib.SocketListener(
                    config.MESH_SOCKET_HOST)
            if self.mode != 'thread':
                self._model_config_overrides = \
                    self._process_config_overrides(model)
            for i in range(n):
                rid = 'r%d' % i
                if self.mode == 'thread':
                    engine = ServingEngine(
                        config, model.trainer, model.params, model.vocabs,
                        decode_table=model._target_index_to_word,
                        tiers=tiers,
                        deadline_ms=0.0, queue_bound=-1,
                        canary_batches=self.canary_batches,
                        canary_agreement=self.canary_agreement,
                        param_source=self._param_source,
                        params_step=self._params_step,
                        tracer=self._tracer,
                        tracing_sample_rate=(0.0 if self._tracer is None
                                             else None),
                        replica_id=rid, external_dispatch=True,
                        on_batch_done=self._on_batch_done,
                        log=self.log)
                    transport = _ThreadReplica(engine)
                    device_indices = None
                else:
                    device_indices = self._allocate_slice_locked()
                    transport = self._spawn_worker(rid, device_indices)
                slot = _ReplicaSlot(rid, transport)
                slot.device_indices = device_indices
                self._replicas.append(slot)
            for slot in self._replicas:
                # process workers spawned above cold-start in parallel;
                # this pass just collects their 'ready' handshakes
                slot.transport.wait_ready()
        except BaseException:
            self._queue.close()
            for slot in self._replicas:
                try:
                    slot.transport.close()
                except BaseException:
                    pass
            if self._listener is not None:
                self._listener.close()
            self._aux_pool.shutdown(wait=False)
            raise
        self.replicas_gauge.set(n)
        if tele_core.enabled():
            tele_core.registry().gauge('mesh/replicas').set(n)
        self._set_serving_gauge_locked_free()
        self._set_live_gauge_locked_free()
        for slot in self._replicas:
            slot.thread = threading.Thread(
                target=self._pull_loop, args=(slot, slot.transport),
                daemon=True, name='mesh-pull-%s' % slot.rid)
            slot.thread.start()
        if self.mode != 'thread':
            # the self-healing layer: supervisor restarts dead workers
            # under the window-scoped budget; the liveness monitor
            # detects hung/partitioned workers the breaker cannot see
            self._supervisor = threading.Thread(
                target=self._supervise_loop, daemon=True,
                name='mesh-supervisor')
            self._supervisor.start()
            if self.heartbeat_secs > 0:
                self._liveness_thread = threading.Thread(
                    target=self._liveness_loop, daemon=True,
                    name='mesh-liveness')
                self._liveness_thread.start()
        if self.mode == 'socket':
            # adoption (SERVING.md "Elastic fleet"): dial-ins with a
            # rid the mesh never spawned are externally-owned workers
            # asking to join; this thread validates and seats them
            self._adopt_thread = threading.Thread(
                target=self._adoption_loop, daemon=True,
                name='mesh-adopt')
            self._adopt_thread.start()
        if config.AUTOSCALE_MAX_REPLICAS > 0:
            from code2vec_tpu.serving.autoscaler import Autoscaler
            self._autoscaler = Autoscaler(self, config,
                                          tracer=self._tracer,
                                          log=self.log)
            self._autoscaler.start()

    def _allocate_slice_locked(self) -> Optional[List[int]]:
        """First free device slice of the placement table (None with
        placement off).  Slices held by non-retired slots are taken —
        a retired slot's slice is free for the next scale-up; a
        restart reuses its own slot's slice without coming here."""
        if self._placement is None:
            return None
        used = {tuple(s.device_indices) for s in self._replicas
                if s.device_indices is not None and not s.retired}
        for indices in self._placement:
            if tuple(indices) not in used:
                return list(indices)
        raise RuntimeError(
            'no free device slice: %d slices of %d device(s) are all '
            'held by serving replicas (raise AUTOSCALE_MAX_REPLICAS/'
            'MESH_REPLICAS only as far as the placement table allows)'
            % (len(self._placement), self.devices_per_replica))

    def _spawn_worker(self, rid: str,
                      device_indices: Optional[List[int]] = None
                      ) -> '_WorkerReplica':
        """One worker transport (initial fleet build, supervised
        restart AND autoscaler scale-up): the worker cold-starts from
        the checkpoint store and reports ready over the framed wire."""
        if faults.maybe_fire('spawn_fail'):
            raise RuntimeError(
                'FAULT_INJECT spawn_fail: worker %s spawn refused '
                'before process start' % rid)
        overrides = dict(self._model_config_overrides)
        if overrides.get('MESH_TELEMETRY_BACKHAUL', -1) == -1:
            # resolve the backhaul AUTO at SPAWN time, not mesh build:
            # a telemetry enable after the mesh came up must reach
            # every later-restarted (or scaled-up) worker, or the
            # fleet merge silently stays partial
            overrides['MESH_TELEMETRY_BACKHAUL'] = (
                1 if tele_core.enabled() else 0)
        if device_indices:
            # placement: the worker builds its sub-mesh over exactly
            # this slice (parallel/mesh.py create_mesh)
            overrides['MESH_DEVICE_INDICES'] = ','.join(
                str(i) for i in device_indices)
        if self._listener is not None:
            # register the rid BEFORE the process exists: a dial-in
            # racing this registration must land in the claim table,
            # not the adoption queue
            self._listener.expect(rid)
        return _WorkerReplica(
            rid, self.mode, overrides,
            on_batch_done=self._on_worker_batch_done,
            on_worker_dead=self._on_worker_dead,
            on_telemetry=self._on_worker_telemetry,
            on_spans=self._note_stitched,
            listener=self._listener, log=self.log)

    # ------------------------------------------------- process plumbing
    def _process_config_overrides(self, model) -> Dict[str, object]:
        """The config a process replica rebuilds its model from: the
        parent's fields, pointed at the parent's checkpoint path
        (pytrees don't cross processes; params come from the store)."""
        import dataclasses
        config = model.config
        load_path = (config.MODEL_LOAD_PATH if config.is_loading
                     else config.MODEL_SAVE_PATH
                     if config.is_saving else None)
        if load_path is None:
            raise RuntimeError(
                "MESH_REPLICA_MODE='%s' needs a checkpointed model "
                '(a --save or --load path with at least one retained '
                'step): worker processes restore params from the store, '
                'they cannot share the parent\'s arrays' % self.mode)
        overrides = {}
        for field in dataclasses.fields(type(config)):
            value = getattr(config, field.name, None)
            if isinstance(value, (bool, int, float, str, type(None))):
                overrides[field.name] = value
        overrides['MODEL_LOAD_PATH'] = load_path
        overrides['MODEL_SAVE_PATH'] = ''
        overrides['TRAIN_DATA_PATH_PREFIX'] = ''
        overrides['SERVE_FOLLOW_CHECKPOINTS_SECS'] = 0.0
        # the worker beats at the MESH's resolved period, not whatever
        # the config default says — a constructor override that only
        # reached the liveness monitor would make a healthy worker
        # look dead (monitor dividing by a shorter period than the
        # worker beats at) and grind the restart budget down
        overrides['MESH_HEARTBEAT_SECS'] = self.heartbeat_secs
        # the worker warms the MESH's resolved tiers, not whatever the
        # parent's SERVING_WARM_TIERS default says — a tier the caller
        # added (submit_neighbors' 'vectors') must be warm in every
        # replica, or its first dispatch compiles on the serving path
        overrides['SERVING_WARM_TIERS'] = ','.join(self.tiers)
        return overrides

    # -------------------------------------------- fleet observability
    def _note_stitched(self, adopted: int, dropped: int) -> None:
        """Stitching accounting (receiver threads): spans grafted into
        live traces vs arrived too late to stitch."""
        if adopted:
            self.adopted_spans_total.inc(adopted)
        if dropped:
            self.remote_spans_dropped_total.inc(dropped)
        if tele_core.enabled():
            reg = tele_core.registry()
            if adopted:
                reg.counter('tracing/adopted_spans_total').inc(adopted)
            if dropped:
                reg.counter(
                    'tracing/remote_spans_dropped_total').inc(dropped)

    def _note_retired(self, reason: str) -> None:
        """Retirement accounting: the unlabeled total plus a
        reason-labeled series (mirrors the dispatch_share labeling
        idiom) — a post-mortem can tell budget-retire from drain from
        an orchestrator-owned worker exiting."""
        self.retired_total.inc()
        if tele_core.enabled():
            from code2vec_tpu.telemetry import catalog
            reg = tele_core.registry()
            reg.counter('mesh/retired_total').inc()
            reg.counter(catalog.labeled(
                'mesh/retired_total', 'reason', reason)).inc()

    def _on_worker_telemetry(self, transport, snapshot,
                             ledger) -> None:
        """Fleet merge (one worker heartbeat): label the worker's
        registry snapshot with its replica id and fold it into THIS
        process's registry, so the existing JSONL/Prometheus exporters
        emit ONE fleet export — worker series land exactly where a
        thread-mode replica's ScopedRegistry mirror would put them.
        Counters merge by delta (a restarted incarnation resets its
        own counts; the fleet series keeps accumulating), gauges by
        last-write, timers as MirrorTimer stat adoptions."""
        del ledger  # rides transport.ledger_info for stats(); the
        #             mem/* gauges arrive via the snapshot itself
        self.worker_snapshots_total.inc()
        if not tele_core.enabled():
            return
        from code2vec_tpu.telemetry import catalog
        reg = tele_core.registry()
        reg.counter('mesh/worker_snapshots_total').inc()
        reg.gauge(catalog.labeled(
            'mesh/clock_offset_ms', 'replica', transport.rid)).set(
                transport.clock.offset * 1e3)
        for name, value in (snapshot or {}).items():
            base, label = catalog.split_label(name)
            meta = catalog.CATALOG.get(base)
            if meta is None:
                continue  # uncataloged names never enter the export
            target = (name if label is not None else
                      catalog.labeled(name, 'replica', transport.rid))
            if isinstance(value, dict):
                reg.mirror_timer(target).adopt(value)
            elif meta['type'] == catalog.COUNTER:
                last = transport._merge_last.get(target, 0)
                delta = value if value < last else value - last
                transport._merge_last[target] = value
                if delta:
                    reg.counter(target).inc(int(delta))
            else:
                try:
                    reg.gauge(target).set(float(value))
                except (TypeError, ValueError):
                    continue

    # ----------------------------------------------------- fleet rate
    def _fleet_rate(self) -> float:
        with self._lock:
            return self._service_rows_per_s

    def _note_service_locked(self, rows: int,
                             taken: List[_Request]) -> None:
        """The engine's windowed throughput estimator
        (engine.note_service_window), fed by EVERY replica's
        completions: the window sum over its span IS the fleet-wide
        served-rows/s the shared admission divides deadlines by."""
        oldest = (min(request.t_enqueue for request in taken)
                  if taken else None)
        self._service_window_rows, self._service_rows_per_s = \
            engine_lib.note_service_window(
                self._service_window, self._service_window_rows,
                self._service_rows_per_s, rows, oldest)

    # ------------------------------------------------ replica weighting
    def _slot_cap_locked(self, slot: _ReplicaSlot) -> int:
        """In-flight window of one replica — the dispatch weight.  A
        canarying replica is halved (still pulling: the canary needs
        live traffic), a half-open breaker probes ONE batch."""
        if slot.breaker_state == _BREAKER_HALF_OPEN:
            return 1
        if slot.canarying:
            return max(1, self.max_inflight // 2)
        return self.max_inflight

    def _slot_ready_locked(self, slot: _ReplicaSlot,
                           transport) -> str:
        """'ready' | 'wait' | 'exit' for one puller iteration."""
        if slot.retired or slot.dead or slot.transport is not transport:
            return 'exit'  # dead/replaced incarnation: its puller dies
        if self._closed and not self._drain:
            return 'exit'
        if slot.breaker_state == _BREAKER_OPEN:
            if time.perf_counter() >= slot.breaker_open_until:
                slot.breaker_state = _BREAKER_HALF_OPEN
                self.log('mesh: replica %s breaker half-open (probing '
                         'one batch)' % slot.rid)
            else:
                return 'wait'
        if slot.inflight >= self._slot_cap_locked(slot):
            return 'wait'
        return 'ready'

    def _slot_alive(self, slot: _ReplicaSlot, transport) -> bool:
        """The queue-side claim check a puller passes to
        ``pop_coalesced``: a replica that retired, died, was replaced,
        or tripped its breaker while waiting must leave WITHOUT taking
        work."""
        with self._lock:
            return not (slot.retired or slot.dead
                        or slot.transport is not transport
                        or slot.breaker_state == _BREAKER_OPEN
                        or (self._closed and not self._drain))

    def _set_serving_gauge_locked_free(self) -> None:
        # reads immutable-ish counts outside the lock on purpose: the
        # gauge is advisory, and both call paths immediately follow a
        # locked mutation
        serving = sum(1 for slot in self._replicas
                      if not slot.retired and not slot.dead
                      and slot.breaker_state != _BREAKER_OPEN)
        self.serving_gauge.set(serving)
        if tele_core.enabled():
            tele_core.registry().gauge(
                'mesh/replicas_serving').set(serving)

    def _set_live_gauge_locked_free(self) -> None:
        # the liveness verdict, distinct from dispatch health: a
        # breaker-open replica is still LIVE (its worker heartbeats),
        # a dead one is not.  Thread replicas share this process's
        # liveness by construction.
        live = sum(1 for slot in self._replicas
                   if not slot.retired and not slot.dead)
        self.live_gauge.set(live)
        if tele_core.enabled():
            tele_core.registry().gauge('mesh/replicas_live').set(live)

    # -------------------------------------------------------- pull loop
    def _pull_loop(self, slot: _ReplicaSlot, transport) -> None:
        # `transport` pins this puller to ONE incarnation: after a
        # supervised restart the slot carries a fresh transport and a
        # fresh puller — a straggler from the dead incarnation exits
        # instead of dispatching onto a wire it no longer owns
        while True:
            with self._cond:
                while True:
                    state = self._slot_ready_locked(slot, transport)
                    if state == 'exit':
                        return
                    if state == 'ready':
                        break
                    # bounded wait: breaker cooldowns expire on the
                    # clock, not on a notification
                    self._cond.wait(0.05)
            popped = self._queue.pop_coalesced(
                self.buckets[-1], self.max_delay_s,
                alive=lambda: self._slot_alive(slot, transport),
                claim=transport)
            if popped is None:
                # depth read BEFORE taking the mesh lock: pop_coalesced
                # holds the queue lock while it calls back into the
                # mesh's alive() (queue->mesh order), so the mesh lock
                # must never wait on the queue lock (AB-BA deadlock); a
                # stale depth just loops once more
                depth = self._queue.depth_rows()
                with self._lock:
                    if slot.retired or slot.dead or \
                            slot.transport is not transport or \
                            (self._closed and not self._drain):
                        return
                    if self._closed and depth == 0:
                        return
                continue
            tier, taken, rows, expired = popped
            for request in expired:
                request.fail(DeadlineExceeded(
                    'request expired after %.0fms in the mesh queue '
                    '(SLO deadline %.0fms)'
                    % (1e3 * (time.perf_counter() - request.t_enqueue),
                       1e3 * (request.t_deadline - request.t_enqueue))))
            if not taken:
                continue  # a sibling drained the tier during coalesce
            with self._cond:
                slot.inflight += 1
                probing = slot.breaker_state == _BREAKER_HALF_OPEN
            try:
                transport.dispatch(tier, taken, rows)
            except BaseException as exc:
                # the member requests are already handled (thread mode:
                # dispatch_external failed them typed; worker mode: the
                # wire-send failure routed them through crash-safe
                # redispatch); here the BREAKER accounts the replica
                # failure
                self._dispatch_failed(slot, rows, probing, exc)
                continue
            # completion: thread transport via the engine's decode
            # worker (_on_batch_done), worker transports via their
            # receiver thread — nothing more to do here either way

    def _dispatch_failed(self, slot: _ReplicaSlot, rows: int,
                         probing: bool, exc: BaseException) -> None:
        del rows, probing
        with self._cond:
            slot.inflight = max(0, slot.inflight - 1)
            self._breaker_failure_locked(slot)
            self._cond.notify_all()
        self._queue.kick()
        self.log('mesh: replica %s dispatch failed (%s): %d consecutive'
                 % (slot.rid, exc, slot.breaker_fails))

    def _breaker_failure_locked(self, slot: _ReplicaSlot) -> None:
        slot.breaker_fails += 1
        if slot.breaker_state == _BREAKER_HALF_OPEN or \
                slot.breaker_fails >= self.breaker_threshold:
            if slot.breaker_state != _BREAKER_OPEN:
                self.breaker_open_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/replica_breaker_open_total').inc()
                self.log('mesh: replica %s dispatch breaker OPEN for '
                         '%.0fs (%d consecutive failures); queue '
                         'redirects to the remaining replicas'
                         % (slot.rid, self.breaker_cooldown_s,
                            slot.breaker_fails))
            slot.breaker_state = _BREAKER_OPEN
            slot.breaker_open_until = (time.perf_counter()
                                       + self.breaker_cooldown_s)
        self._set_serving_gauge_locked_free()

    def _on_batch_done(self, engine, rows: int, taken: List[_Request],
                       ok: bool) -> None:
        """Thread-mode completion hook (runs on the replica engine's
        decode worker)."""
        slot = next(s for s in self._replicas
                    if isinstance(s.transport, _ThreadReplica)
                    and s.transport.engine is engine)
        self._complete(slot, rows, taken, ok)

    def _on_worker_batch_done(self, transport, rows: int,
                              taken: List[_Request], ok: bool) -> None:
        slot = next((s for s in self._replicas
                     if s.transport is transport), None)
        if slot is None:
            return  # a stale completion from a replaced incarnation
        self._complete(slot, rows, taken, ok)

    # ------------------------------------------------------ self-healing
    def _on_worker_dead(self, transport,
                        pending: List[Tuple[List[_Request], int]],
                        reason: BaseException) -> None:
        """A worker replica died — EOF, a corrupt frame, a wire-send
        failure, or a liveness kill.  Mark the slot dead TYPED (the
        supervisor restarts it under the budget; the breaker's
        half-open probe never sacrifices a real micro-batch to a
        corpse), then crash-safe-redispatch the batches that died with
        it: members are re-admitted ONCE at the front of the shared
        queue with this incarnation excluded and their deadlines
        intact, so the crash costs latency, not answers."""
        adopted_exit = False
        with self._cond:
            slot = next((s for s in self._replicas
                         if s.transport is transport), None)
            if slot is not None and not slot.retired and not slot.dead:
                slot.dead = True
                slot.inflight = 0
                if slot.adopted:
                    # restart supervision for an adopted worker belongs
                    # to the ORCHESTRATOR that spawned it: retire the
                    # slot instead of charging the LOCAL restart budget
                    # (a redial lands as a fresh adoption); its
                    # in-flight batches still redispatch below
                    slot.retired = True
                    slot.retired_reason = 'adopted_worker_exit'
                    adopted_exit = True
                self._cond.notify_all()  # puller exits, supervisor wakes
        requeued = failed = 0
        for taken, _rows in pending:
            got = self._redispatch_batch(transport, slot, taken, reason)
            requeued += got
            failed += len(taken) - got
        if adopted_exit:
            self._note_retired('adopted_worker_exit')
        self._set_serving_gauge_locked_free()
        self._set_live_gauge_locked_free()
        self._queue.kick()
        if adopted_exit:
            self.log('mesh: ADOPTED replica %s worker exited (%s): %d '
                     'request(s) redispatched, %d failed typed; its '
                     'orchestrator owns the restart — the local budget '
                     'is not charged'
                     % (slot.rid, reason, requeued, failed))
            self._fail_queue_if_fleet_empty()
        else:
            self.log('mesh: replica %s worker DEAD (%s): %d request(s) '
                     'redispatched to the front of the queue, %d failed '
                     'typed; supervisor will restart it within the '
                     'budget'
                     % (slot.rid if slot is not None else '?', reason,
                        requeued, failed))
        try:
            transport.reap()  # the corpse: SIGKILL + join, no handshake
        except Exception:
            pass

    def _redispatch_batch(self, token, slot: Optional[_ReplicaSlot],
                          taken: List[_Request],
                          reason: BaseException) -> int:
        """Re-admit the members of one crashed batch at the FRONT of
        their tier queue (once per request — a second crash fails them
        typed ``ReplicaDead``).  Returns how many were re-admitted."""
        survivors: List[_Request] = []
        for request in taken:
            if request.trace is not None and \
                    request.queue_span is not None:
                # the wire-send-failure path reaches here with the
                # FIRST queue_wait span still open (the hand-off close
                # only runs after a successful send): end it so the
                # redispatch attempt's span doesn't orphan it
                request.trace.end(request.queue_span)
                request.queue_span = None
            if request.redispatched:
                request.fail(ReplicaDead(
                    'request lost its replica twice (%s); failing '
                    'typed instead of bouncing forever' % reason))
                continue
            request.redispatched = True
            request.exclude = token
            if request.trace is not None:
                # the trace shows BOTH attempts: the first queue_wait/
                # dispatch, this event, then a second queue_wait
                request.trace.event(
                    'serving.redispatch', parent=request.span_parent,
                    attrs={'replica': slot.rid if slot else '?',
                           'reason': str(reason)})
                request.queue_span = request.trace.span(
                    'serving.queue_wait', parent=request.span_parent)
            survivors.append(request)
        if not survivors:
            return 0
        if not self._queue.requeue_front(survivors[0].tier, survivors):
            # mesh closed fail-fast between death and redispatch
            for request in survivors:
                request.fail(EngineClosed(
                    'ServingMesh closed before the crashed batch could '
                    'be redispatched'))
            return 0
        self.redispatched_total.inc(len(survivors))
        if tele_core.enabled():
            tele_core.registry().counter(
                'mesh/redispatched_total').inc(len(survivors))
        return len(survivors)

    def _liveness_loop(self) -> None:
        """Heartbeat monitor: liveness DISTINCT from dispatch health.
        A hung or partitioned worker with nothing in flight looks
        healthy to the breaker (no dispatch fails); its missing
        heartbeats are what betray it.  Past the miss budget the
        replica is killed — the receiver's EOF then runs the one death
        path (redispatch + supervised restart)."""
        period = self.heartbeat_secs
        while not self._close_event.wait(period):
            if self._slo is not None:
                # periodic burn-gauge refresh: exported burns decay
                # after traffic stops instead of freezing at the last
                # burst's value
                self._slo.refresh()
            now = time.perf_counter()
            with self._cond:
                watched = [(s, s.transport) for s in self._replicas
                           if not s.retired and not s.dead
                           and not s.restarting
                           and isinstance(s.transport, _WorkerReplica)]
            for slot, transport in watched:
                missed = (now - transport.last_heartbeat) / period
                if missed < 1.0:
                    continue
                self.heartbeat_misses_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/heartbeat_misses_total').inc()
                if missed > self.heartbeat_misses:
                    self.log('mesh: replica %s missed %d heartbeats '
                             '(budget %d) — hung or partitioned; '
                             'marking dead and killing the worker'
                             % (slot.rid, int(missed),
                                self.heartbeat_misses))
                    # the kill forces the receiver's EOF: death
                    # handling (redispatch + supervisor) runs there
                    # exactly once
                    transport.kill()

    def _supervise_loop(self) -> None:
        """Supervised restart: a dead locally-spawned worker comes back
        on its own — exponential backoff, a window-scoped restart
        budget (a flapping worker retires permanently instead of
        storming), cold start from the checkpoint store, then
        re-adoption onto the fleet's CURRENT params step before its
        puller touches the queue."""
        while True:
            retire = False
            with self._cond:
                slot = None
                while slot is None:
                    if self._closed:
                        return
                    slot = next((s for s in self._replicas
                                 if s.dead and not s.retired
                                 and not s.adopted
                                 and not s.restarting), None)
                    if slot is None:
                        self._cond.wait(0.2)
                now = time.perf_counter()
                while slot.restart_times and \
                        now - slot.restart_times[0] > self.restart_window_s:
                    slot.restart_times.popleft()
                if len(slot.restart_times) >= self.restart_limit:
                    slot.retired = True
                    slot.retired_reason = 'restart_budget'
                    retire = True
                else:
                    slot.restarting = True
                    slot.restart_times.append(now)
                attempt = len(slot.restart_times)
                self._cond.notify_all()
            if retire:
                self.log('mesh: replica %s spent its restart budget '
                         '(%d in %.0fs) — retiring permanently; the '
                         'queue serves through the remaining replicas'
                         % (slot.rid, self.restart_limit,
                            self.restart_window_s))
                self._note_retired('restart_budget')
                self._set_serving_gauge_locked_free()
                self._set_live_gauge_locked_free()
                self._fail_queue_if_fleet_empty()
                continue
            backoff = self.restart_backoff_s * (2 ** (attempt - 1))
            if backoff > 0 and self._close_event.wait(min(backoff, 30.0)):
                with self._cond:
                    slot.restarting = False
                return
            self.log('mesh: restarting replica %s (attempt %d in '
                     'window, backoff %.2fs)'
                     % (slot.rid, attempt, backoff))
            transport = None
            try:
                # a placed replica restarts onto ITS OWN slice: the
                # warm ladder it cold-starts is placement-identical to
                # the incarnation it replaces
                transport = self._spawn_worker(slot.rid,
                                               slot.device_indices)
                with self._lock:
                    self._restart_pending = transport
                if self._close_event.is_set():
                    # close() may have read _restart_pending before the
                    # assignment above: cancel ourselves so the cold
                    # start is never leaked
                    transport.cancel()
                transport.wait_ready()
                # the worker cold-started from the checkpoint store;
                # re-adopt it onto the fleet's CURRENT step — which may
                # have rolled while it was down — BEFORE it pulls.  An
                # in-flight rollover concludes first, so the step read
                # here is the one the fleet actually settled on.
                with self._cond:
                    while self._rollover is not None and \
                            not self._closed:
                        self._cond.wait(0.1)
                    fleet_step = self._params_step
                worker_step = transport.ready_info.get('params_step')
                if fleet_step is not None and worker_step != fleet_step:
                    self.log('mesh: replica %s rejoined at step %s; '
                             're-adopting the fleet\'s current step %d'
                             % (slot.rid, worker_step, fleet_step))
                    transport.adopt(None, fleet_step, fleet_step)
            except BaseException as exc:
                with self._lock:
                    self._restart_pending = None
                if transport is not None:
                    try:
                        transport.reap()
                    except Exception:
                        pass
                with self._cond:
                    slot.restarting = False  # still dead: retry/budget
                if self._close_event.is_set():
                    return
                self.log('mesh: replica %s restart failed (%r); '
                         'retrying under the budget' % (slot.rid, exc))
                continue
            with self._cond:
                self._restart_pending = None
                if self._closed:
                    closed = True
                else:
                    closed = False
                    slot.transport = transport
                    slot.dead = False
                    slot.restarting = False
                    slot.inflight = 0
                    slot.breaker_fails = 0
                    slot.breaker_state = _BREAKER_CLOSED
                    slot.restarts += 1
                    slot.thread = threading.Thread(
                        target=self._pull_loop, args=(slot, transport),
                        daemon=True, name='mesh-pull-%s' % slot.rid)
                    slot.thread.start()
                    self._cond.notify_all()
            if closed:
                transport.close()
                return
            self.restarts_total.inc()
            if tele_core.enabled():
                tele_core.registry().counter('mesh/restarts_total').inc()
            self._set_serving_gauge_locked_free()
            self._set_live_gauge_locked_free()
            self._queue.kick()
            self.log('mesh: replica %s restarted and rejoined the '
                     'fleet (serving step %s)'
                     % (slot.rid,
                        transport.ready_info.get('params_step')
                        if fleet_step is None else fleet_step))

    def _fail_queue_if_fleet_empty(self) -> None:
        """Every replica permanently retired: admitted work can never
        be served — close the queue and fail it typed instead of
        hanging.  Closing (not just abandoning) also covers the racing
        submitter that passed submit's unlocked all-retired check
        before the last retirement landed: its enqueue re-checks the
        queue's closed flag and raises typed, so nothing can ever
        strand in a queue with zero pullers."""
        with self._cond:
            if not all(s.retired for s in self._replicas):
                return
            self._closed = True  # no replica can ever serve again
            self._cond.notify_all()
        self.log('mesh: NO serving replicas remain; failing the queue '
                 'typed')
        self._queue.close()
        for request in self._queue.abandon():
            request.fail(ReplicaDead(
                'every mesh replica has retired; the queue cannot '
                'drain'))

    # --------------------------------------------------- elastic fleet
    def add_replica(self) -> str:
        """Scale the fleet UP by one locally-built replica (the
        autoscaler's spawn leg; also a public operator verb).  Worker
        modes spawn + cold-start a new worker — on its own device
        slice under placement — and re-adopt it onto the fleet's
        CURRENT params step before its puller touches the queue;
        thread mode builds a sibling engine over the shared trainer
        (cache-hit warmup, zero new compiles).  Returns the new rid."""
        with self._cond:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            rid = 'r%d' % self._next_rid
            self._next_rid += 1
            device_indices = (None if self.mode == 'thread'
                              else self._allocate_slice_locked())
            seed_step = self._params_step
        if self.mode == 'thread':
            model = self._model
            engine = ServingEngine(
                self.config, model.trainer, model.params, model.vocabs,
                decode_table=model._target_index_to_word,
                tiers=self.tiers,
                deadline_ms=0.0, queue_bound=-1,
                canary_batches=self.canary_batches,
                canary_agreement=self.canary_agreement,
                param_source=self._param_source,
                params_step=seed_step,
                tracer=self._tracer,
                tracing_sample_rate=(0.0 if self._tracer is None
                                     else None),
                replica_id=rid, external_dispatch=True,
                on_batch_done=self._on_batch_done,
                log=self.log)
            engine.warmup()  # trainer jit caches: cache-hit, 0 compiles
            transport = _ThreadReplica(engine)
            # the model's pytree may predate a fleet rollover: adopt
            # the CURRENT params from a serving sibling (pointer swap)
            with self._cond:
                donor = next(
                    (s for s in self._replicas
                     if isinstance(s.transport, _ThreadReplica)
                     and not s.retired and not s.dead), None)
                step = self._params_step
            if donor is not None:
                engine.adopt_params(donor.transport.engine.params,
                                    step=step)
        else:
            transport = self._spawn_worker(rid, device_indices)
            try:
                transport.wait_ready()
                # wait out an in-flight rollover, then serve the step
                # the fleet settled on (the supervisor's re-adoption
                # leg, reused for scale-up)
                with self._cond:
                    while self._rollover is not None and \
                            not self._closed:
                        self._cond.wait(0.1)
                    fleet_step = self._params_step
                worker_step = transport.ready_info.get('params_step')
                if fleet_step is not None and worker_step != fleet_step:
                    transport.adopt(None, fleet_step, fleet_step)
            except BaseException:
                try:
                    transport.reap()
                except Exception:
                    pass
                raise
        self._seat_replica(rid, transport, device_indices,
                           adopted=False)
        self.log('mesh: scaled UP — replica %s joined the fleet%s'
                 % (rid, (' on devices %s' % (device_indices,))
                    if device_indices else ''))
        return rid

    def _seat_replica(self, rid: str, transport,
                      device_indices: Optional[List[int]],
                      adopted: bool) -> None:
        """Append a ready transport to the replica table and start its
        puller (scale-up and adoption share this tail)."""
        with self._cond:
            if self._closed:
                closed = True
            else:
                closed = False
                slot = _ReplicaSlot(rid, transport)
                slot.adopted = adopted
                slot.device_indices = device_indices
                self._replicas.append(slot)
                slot.thread = threading.Thread(
                    target=self._pull_loop, args=(slot, transport),
                    daemon=True, name='mesh-pull-%s' % rid)
                slot.thread.start()
                self._cond.notify_all()
        if closed:
            try:
                transport.close()
            except BaseException:
                pass
            raise EngineClosed('ServingMesh closed during scale-up')
        self.replicas_gauge.set(len(self._replicas))
        if tele_core.enabled():
            tele_core.registry().gauge(
                'mesh/replicas').set(len(self._replicas))
        self._set_serving_gauge_locked_free()
        self._set_live_gauge_locked_free()
        self._queue.kick()

    def _adoption_loop(self) -> None:
        """Socket mode: seat externally-spawned workers.  A dial-in
        whose rid the mesh never registered (``SocketListener``'s
        unclaimed path) is an orchestrator-owned worker asking to
        join: validate its capabilities, re-adopt it onto the fleet's
        current step, and give it a puller — or turn it away typed."""
        while not self._close_event.is_set():
            got = self._listener.wait_adoptable(
                0.25, cancel=self._close_event)
            if got is None:
                continue
            rid, channel, _hello = got
            try:
                self._adopt_dialin(rid, channel)
            except EngineClosed:
                try:
                    channel.close()
                except BaseException:
                    pass
                return
            except BaseException as exc:
                self.adoption_rejected_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/adoption_rejected_total').inc()
                self.log('mesh: adoption of dial-in %r REJECTED: %s'
                         % (rid, exc))
                try:
                    # typed answer before the close: the worker (and
                    # its orchestrator's logs) learn WHY
                    channel.send(('adopt_rejected', str(exc)))
                except BaseException:
                    pass
                try:
                    channel.close()
                except BaseException:
                    pass

    def _adopt_dialin(self, rid: str, channel) -> None:
        """Validate + seat ONE adoptable dial-in (raises
        ``AdoptionRejected`` to turn it away typed)."""
        with self._lock:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if any(s.rid == rid and not s.retired
                   for s in self._replicas):
                raise AdoptionRejected(
                    'rid %r already names a serving replica in this '
                    'fleet; external workers need unique --rid values'
                    % rid)
        transport = _WorkerReplica(
            rid, 'socket', {},
            on_batch_done=self._on_worker_batch_done,
            on_worker_dead=self._on_worker_dead,
            on_telemetry=self._on_worker_telemetry,
            on_spans=self._note_stitched,
            listener=self._listener, log=self.log,
            start_timeout_s=self.adopt_ready_timeout_s,
            channel=channel)
        try:
            transport.wait_ready()
        except BaseException as exc:
            raise AdoptionRejected(
                'worker %r dialed in but never reported ready within '
                '%.0fs: %r' % (rid, self.adopt_ready_timeout_s, exc))
        caps = transport.ready_info.get('capabilities') or {}
        try:
            if caps.get('proto') != transport_lib.WIRE_PROTO:
                raise AdoptionRejected(
                    'worker %r speaks wire proto %r, this mesh speaks '
                    '%d' % (rid, caps.get('proto'),
                            transport_lib.WIRE_PROTO))
            if caps.get('wire') != self.config.BATCH_WIRE_FORMAT:
                raise AdoptionRejected(
                    'worker %r ships batches as %r, this mesh expects '
                    '%r' % (rid, caps.get('wire'),
                            self.config.BATCH_WIRE_FORMAT))
            missing = set(self.tiers) - set(caps.get('tiers') or ())
            if missing:
                raise AdoptionRejected(
                    'worker %r did not warm tier(s) %s this mesh '
                    'serves; its first dispatch there would compile on '
                    'the serving path' % (rid, sorted(missing)))
            # re-adopt onto the fleet's CURRENT step — an adoption
            # landing mid-rollover waits the rollover out first, so
            # the step read here is the one the fleet settled on
            with self._cond:
                while self._rollover is not None and not self._closed:
                    self._cond.wait(0.1)
                if self._closed:
                    raise EngineClosed('ServingMesh is closed')
                fleet_step = self._params_step
            worker_step = transport.ready_info.get('params_step')
            if fleet_step is not None and worker_step != fleet_step:
                self.log('mesh: adopting %s at step %s; re-adopting '
                         'the fleet\'s current step %d'
                         % (rid, worker_step, fleet_step))
                transport.adopt(None, fleet_step, fleet_step)
        except BaseException as exc:
            try:
                # typed answer BEFORE tearing the wire down (cancel
                # closes the channel; the adoption loop's fallback
                # send would find it already gone)
                channel.send(('adopt_rejected', str(exc)))
            except BaseException:
                pass
            try:
                transport.cancel()  # stop the receiver; close the wire
            except BaseException:
                pass
            raise
        devices = caps.get('devices')
        self._seat_replica(rid, transport,
                           list(devices) if devices else None,
                           adopted=True)
        self.adopted_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter('mesh/adopted_total').inc()
        self.log('mesh: ADOPTED externally-spawned worker %s (step %s, '
                 'devices %s); restart supervision stays with its '
                 'orchestrator'
                 % (rid, transport.ready_info.get('params_step'),
                    devices))

    def _complete(self, slot: _ReplicaSlot, rows: int,
                  taken: List[_Request], ok: bool) -> None:
        with self._cond:
            # clamp: a partitioned worker's late delivery can land
            # after its death handler already zeroed the window
            slot.inflight = max(0, slot.inflight - 1)
            if ok:
                slot.breaker_fails = 0
                if slot.breaker_state != _BREAKER_CLOSED:
                    slot.breaker_state = _BREAKER_CLOSED
                    self.log('mesh: replica %s breaker closed (probe '
                             'succeeded)' % slot.rid)
                    self._set_serving_gauge_locked_free()
                slot.rows_dispatched += rows
                slot.batches += 1
                self._rows_total += rows
                self._note_service_locked(rows, taken)
                if tele_core.enabled() and self._rows_total > 0:
                    # per-replica dispatch share: replica-labeled series
                    # under one catalog family
                    from code2vec_tpu.telemetry import catalog
                    tele_core.registry().gauge(catalog.labeled(
                        'mesh/dispatch_share', 'replica',
                        slot.rid)).set(
                            slot.rows_dispatched / self._rows_total)
            else:
                self._breaker_failure_locked(slot)
            self._cond.notify_all()
        self._queue.kick()

    # ----------------------------------------------------------- submit
    def submit(self, context_lines: Sequence[str], tier: str = 'topk',
               deadline_ms: Optional[float] = None,
               scenario: Optional[str] = None,
               language: Optional[str] = None,
               record: bool = True, observe: bool = True) -> Future:
        """Enqueue one prediction request on the SHARED front queue;
        whichever free replica claims it serves it.  Same contract as
        ``ServingEngine.submit`` (typed sheds, oversize split, Future
        of one result per line).

        ``scenario``/``language`` tag the request for the scenario
        traffic plane (WORKLOADS.md): the scenario rides the trace root
        attrs (and from there the dispatch context), labels the memo
        hit/miss mirrors and the SLO observations.  ``record=False``
        skips the admission traffic tap, ``observe=False`` skips the
        SLO observation — both used by composing entry points
        (``submit_neighbors``/``submit_blended``) that tap and observe
        once at their own outer future."""
        if tier not in self.tiers:
            raise ValueError('tier %r is not warmed on this mesh '
                             '(tiers=%s)' % (tier, list(self.tiers)))
        # retirement is monotonic, so this unlocked scan can only be
        # conservatively stale: once every replica has permanently
        # retired, admitting more work would hang it forever (checked
        # before the generic closed flag — the fleet-empty path sets
        # both, and the specific reason is the useful one)
        if all(slot.retired for slot in self._replicas):
            raise EngineClosed(
                'every mesh replica has retired (restart budgets '
                'spent); the mesh cannot serve')
        # graftlint: disable=lock-discipline -- benign racy fast-fail: a close() racing past this read is re-checked inside FrontQueue.enqueue
        if self._closed:
            raise EngineClosed('ServingMesh is closed')
        t_submit0 = time.perf_counter()
        # ONE definition of request identity across engine + mesh +
        # memo key (data/reader.py canonicalize_contexts; idempotent at
        # fixed MAX_CONTEXTS — process_input_rows applies it again at
        # tokenize).  MAX_CONTEXTS must reach the FIRST call: it
        # truncates in extraction order before the canonical sort.
        lines = canonicalize_contexts(context_lines,
                                      self.config.MAX_CONTEXTS)
        future: Future = Future()
        if not lines:
            future.set_result([])
            return future
        if record:
            self._record_traffic(scenario or 'softmax_naming', lines,
                                 language=language, tier=tier)
        n = len(lines)
        if deadline_ms is None:
            deadline_s = self.deadline_s
        else:
            deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None
        self.requests_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter('mesh/requests_total').inc()
        trace = None
        if self._tracer is not None:
            attrs = {'tier': tier, 'rows': n, 'mesh': True,
                     'deadline_ms': (1e3 * deadline_s
                                     if deadline_s else None)}
            if scenario is not None:
                attrs['scenario'] = scenario
            trace = self._tracer.begin('serving.request', attrs=attrs)
        requested_tier = tier
        # memoization tier: content-addressed exact lookup BEFORE
        # tokenize and FrontQueue.admit — a hit resolves the future
        # right here, costing zero device-seconds and no queue slot
        memo = self._memo
        memo_key = None
        if memo is not None:
            memo_key = memo_lib.request_key(lines, tier)
            # the exact tier STANDS DOWN while a canary is in flight:
            # duplicate-heavy traffic served from cache would starve
            # the canary's shadow scorer of batches and the rollover
            # would never conclude — during a canary every request
            # runs live (inserts still happen; the generation check
            # keeps any result in flight across the swap out)
            rolling = self._rollover is not None  # graftlint: disable=lock-discipline -- benign racy read: a stale None serves one more hit, a stale rollover runs one more request live
            cached = None if rolling else memo.lookup(memo_key,
                                                      scenario=scenario)
            if cached is not None:
                if trace is not None:
                    trace.event('serving.memo_hit',
                                attrs={'tier': tier, 'rows': n,
                                       'memo': 'exact'})
                    trace.finish(status='ok')
                if observe and self._slo is not None:
                    self._slo.observe_good(
                        time.perf_counter() - t_submit0,
                        scenario=scenario)
                # lookup returned a fresh copy (memo_lib.copy_results):
                # mutating it cannot poison later hits on this key
                future.set_result(cached)
                return future
        t_admit0 = time.perf_counter()
        try:
            tier = self._queue.admit(n, tier, deadline_s)
        except EngineOverloaded as exc:
            if trace is not None:
                trace.event('serving.shed', attrs={'reason': str(exc)})
                trace.finish(status='shed')
                self._tracer.note_shed()
            if observe and self._slo is not None:
                self._slo.observe_bad('shed', scenario=scenario)
            raise
        except EngineClosed as exc:
            if trace is not None:
                trace.event('serving.closed', attrs={'reason': str(exc)})
                trace.finish(status='closed')
            raise
        t_admit1 = time.perf_counter()
        if trace is not None:
            trace.span_at('serving.admission', t_admit0, t_admit1)
            if tier != requested_tier:
                trace.event('serving.degraded',
                            attrs={'requested': requested_tier,
                                   'effective': tier})
        try:
            requests = engine_lib.tokenize_and_chunk(
                self._reader, lines, tier, future, deadline_s, trace,
                t_admit1, self.buckets[-1])
        except BaseException as exc:
            self._queue.release_reservation(n)
            if trace is not None:
                trace.finish(status='error', reason=repr(exc))
            raise
        for request in requests:
            if request.trace is not None:
                request.queue_span = request.trace.span(
                    'serving.queue_wait', parent=request.span_parent,
                    t0=request.t_enqueue)
        try:
            self._queue.enqueue(tier, requests, n)
        except EngineClosed:
            if trace is not None:
                trace.event('serving.closed',
                            attrs={'reason': 'ServingMesh is closed'})
                trace.finish(status='closed')
            raise
        if observe and self._slo is not None:
            # one SLO event per CALLER-VISIBLE request, observed at its
            # future — an oversize submit's chunk fan-out must not
            # inflate the good count, and one failed chunk fails the
            # whole answer, burning one full budget unit.  Shed-at-
            # admission is counted at the raise above (the future is
            # never returned); a close-time EngineClosed flood is
            # shutdown, not an SLO violation, and stays out.
            slo, t_admitted, scen = self._slo, t_admit0, scenario

            def _slo_observe(done: Future) -> None:
                try:
                    exc = done.exception()
                except BaseException:
                    return  # caller cancelled: not the server's verdict
                if exc is None:
                    slo.observe_good(time.perf_counter() - t_admitted,
                                     scenario=scen)
                elif not isinstance(exc, EngineClosed):
                    slo.observe_bad(type(exc).__name__, scenario=scen)

            future.add_done_callback(_slo_observe)
        if memo is not None:
            # insert-on-delivery: only a good caller-visible result is
            # cached (fires after oversize chunk re-join); key on the
            # EFFECTIVE tier so a degraded-tier answer can never poison
            # the full-tier key the next caller will look up
            insert_key = (memo_key if tier == requested_tier
                          else memo_lib.request_key(lines, tier))
            generation = memo.generation

            def _memo_insert(done: Future) -> None:
                try:
                    exc = done.exception()
                except BaseException:
                    return  # caller cancelled: nothing was delivered
                if exc is None:
                    memo.insert(insert_key, done.result(), generation)

            future.add_done_callback(_memo_insert)
        return future

    def predict(self, context_lines: Sequence[str], tier: str = 'topk',
                timeout: Optional[float] = None) -> list:
        """Synchronous ``submit().result()`` convenience."""
        return self.submit(context_lines, tier).result(timeout)

    # ------------------------------------------- scenario traffic plane
    def record_traffic(self, recorder) -> 'ServingMesh':
        """Arm (or with ``None`` disarm) the admission traffic tap: a
        ``workloads.profile.ProfileRecorder`` that sees every caller-
        visible submit/submit_neighbors/submit_blended with its scenario
        label, for later durable save + replay (WORKLOADS.md)."""
        self._traffic_recorder = recorder
        return self

    def _record_traffic(self, scenario: str, lines=None, vector=None,
                        language: Optional[str] = None,
                        tier: Optional[str] = None,
                        k: Optional[int] = None,
                        weight: Optional[float] = None) -> None:
        recorder = self._traffic_recorder
        if recorder is None:
            return
        label = None
        if lines:
            # recorded label = the method's true name, recoverable from
            # the context-line head (extractor output contract); lets a
            # replay score quality without a separate label channel
            label = lines[0].split(' ', 1)[0] or None
        try:
            recorder.record(scenario, language=language, lines=lines,
                            vector=vector, label=label, tier=tier,
                            k=k, weight=weight)
        except Exception as exc:  # the tap must never fail a request
            self.log('traffic tap dropped a record: %r' % (exc,))

    # -------------------------------------------------------- neighbors
    def attach_index(self, index) -> 'ServingMesh':
        """Arm ``submit_neighbors``: neighbor queries ride the shared
        dispatch stream's 'vectors' tier, then the attached index (one
        index serves the whole fleet — it is device-resident once)."""
        if 'vectors' not in self.tiers:
            raise ValueError(
                "submit_neighbors needs the 'vectors' tier warmed on "
                'this mesh (tiers=%s)' % list(self.tiers))
        self._index = index
        return self

    def submit_neighbors(self, context_or_vectors,
                         k: Optional[int] = None,
                         scenario: Optional[str] = None,
                         language: Optional[str] = None,
                         record: bool = True,
                         observe: bool = True) -> Future:
        """Mesh analogue of ``ServingEngine.submit_neighbors``: context
        lines ride the micro-batched 'vectors' tier ACROSS the fleet,
        the resulting code vectors feed the shared index.  Scenario
        plumbing as in ``submit``; the inner 'vectors' leg never taps
        or observes on its own (record/observe gating)."""
        index = self._index
        if index is None:
            raise RuntimeError('no index attached — call '
                               'attach_index(load_index(...)) first')
        k = k if k is not None else self.config.INDEX_NEIGHBORS_K
        from code2vec_tpu.index.service import neighbors_from_search
        t_submit0 = time.perf_counter()
        outer: Future = Future()
        memo = self._memo
        scenario_name = scenario or 'neighbor_search'
        # BOTH memo tiers stand down while a canary rollover is in
        # flight, exactly as submit() does: duplicate-heavy neighbors
        # traffic served from cache would starve the canary's shadow
        # scorer of batches and the rollover would never conclude
        # (inserts still happen; the generation check keeps any result
        # in flight across the swap out).  An INDEX rollover stands
        # the neighbor memo down for the same reason: its shadow
        # queries ride live neighbor traffic
        rolling = self._rollover is not None  # graftlint: disable=lock-discipline -- benign racy read: a stale None serves one more hit, a stale rollover runs one more request live
        rolling = rolling or self._index_rollover is not None  # graftlint: disable=lock-discipline -- same benign racy read for the index-rollover axis
        if isinstance(context_or_vectors, np.ndarray):
            vectors = np.atleast_2d(context_or_vectors)
            if record:
                for row in vectors:
                    self._record_traffic(
                        scenario_name, vector=[float(x) for x in row],
                        language=language, k=k)
            shadow_row = None
            if memo is not None and not rolling and vectors.shape[0] == 1:
                # semantic tier: serve a within-epsilon single-row query
                # from a near-identical prior request's cached result
                sem = memo.semantic_lookup(vectors[0], k)
                if sem is not None:
                    sem_row, shadow = sem
                    if not shadow:
                        if self._tracer is not None:
                            attrs = {'tier': 'neighbors', 'rows': 1,
                                     'mesh': True}
                            if scenario is not None:
                                attrs['scenario'] = scenario
                            trace = self._tracer.begin(
                                'serving.request', attrs=attrs)
                            trace.event('serving.memo_hit',
                                        attrs={'tier': 'neighbors',
                                               'rows': 1,
                                               'memo': 'semantic'})
                            trace.finish(status='ok')
                        # cache-served requests stay in the SLO
                        # good-rate denominator, as in submit()
                        if observe and self._slo is not None:
                            self._slo.observe_good(
                                time.perf_counter() - t_submit0,
                                scenario=scenario)
                        outer.set_result([sem_row])
                        return outer
                    # shadow sample: run live anyway, then score the
                    # cached row's top-1 agreement against the live one
                    shadow_row = sem_row
            sem_gen = memo.generation if memo is not None else None
            sem_igen = (memo.index_generation if memo is not None
                        else None)
            # re-read the index AFTER capturing the generation: a
            # rollover concluding between the top-of-function read and
            # here would otherwise search the OLD index yet insert
            # under the NEW generation — a stale cached answer.  This
            # order fails safe: old generation + new index is merely a
            # refused insert
            index = self._index

            def lookup():
                try:
                    values, indices = index.search(vectors, k)
                    self._note_index_shadow(vectors, indices, k)
                    results = neighbors_from_search(
                        values, indices, index.labels)
                    if memo is not None:
                        if shadow_row is not None and results:
                            memo.note_semantic_agreement(
                                shadow_row, results[0])
                        memo.semantic_insert(vectors, results, k,
                                             sem_gen,
                                             index_generation=sem_igen)
                    _resolve(outer, results)
                except BaseException as exc:
                    if not outer.done():
                        outer.set_exception(exc)
            self._aux_pool.submit(lookup)
            return outer
        lines = canonicalize_contexts(context_or_vectors,
                                      self.config.MAX_CONTEXTS)
        if record:
            self._record_traffic(scenario_name, lines,
                                 language=language, k=k)
        nkey = None
        gen = None
        igen = None
        if memo is not None:
            # exact tier for line-based neighbor queries: keyed per k so
            # a k=5 answer can never serve a k=10 ask; stands down
            # during a canary like every other memo serve path
            nkey = memo_lib.request_key(lines, 'neighbors', k=k)
            cached = None if rolling else memo.lookup(nkey,
                                                      scenario=scenario)
            if cached is not None:
                if self._tracer is not None:
                    attrs = {'tier': 'neighbors', 'rows': len(lines),
                             'mesh': True}
                    if scenario is not None:
                        attrs['scenario'] = scenario
                    trace = self._tracer.begin('serving.request',
                                               attrs=attrs)
                    trace.event('serving.memo_hit',
                                attrs={'tier': 'neighbors',
                                       'rows': len(lines),
                                       'memo': 'exact'})
                    trace.finish(status='ok')
                # cache-served requests stay in the SLO good-rate
                # denominator, as in submit()
                if observe and self._slo is not None:
                    self._slo.observe_good(
                        time.perf_counter() - t_submit0,
                        scenario=scenario)
                outer.set_result(cached)
                return outer
            gen = memo.generation
            igen = memo.index_generation
            # re-read AFTER igen — same swap-race ordering as the
            # ndarray path above: never pair the old index with the
            # new generation
            index = self._index
        inner = self.submit(lines, tier='vectors', scenario=scenario,
                            record=False, observe=observe)

        def chain(done: Future) -> None:
            try:
                results = done.result()
                if not results:
                    _resolve(outer, [])
                    return
                vectors = np.stack([r.code_vector for r in results])
                values, indices = index.search(vectors, k)
                self._note_index_shadow(vectors, indices, k)
                out_results = neighbors_from_search(
                    values, indices, index.labels)
                if memo is not None:
                    memo.insert(nkey, out_results, gen,
                                index_generation=igen)
                    memo.semantic_insert(vectors, out_results, k, gen,
                                         index_generation=igen)
                _resolve(outer, out_results)
            except BaseException as exc:
                if not outer.done():
                    outer.set_exception(exc)
        inner.add_done_callback(chain)
        return outer

    # ------------------------------------------- retrieval-augmented
    def submit_blended(self, context_lines: Sequence[str],
                       weight: Optional[float] = None,
                       k: Optional[int] = None,
                       deadline_ms: Optional[float] = None,
                       scenario: Optional[str] = None,
                       language: Optional[str] = None,
                       record: bool = True) -> Future:
        """Retrieval-augmented naming (WORKLOADS.md): blend the softmax
        head's top-k distribution with similarity votes from the
        attached index's top-k neighbor labels.  Returns a Future of
        one ``workloads.blend.BlendResult`` per method.

        Composes the two WARMED paths — ``submit(tier='topk')`` and
        ``submit_neighbors`` — so a blend costs zero new compiles; the
        legs run with ``record=False, observe=False`` and the blend
        registers exactly ONE traffic-tap record and ONE SLO
        observation at its own future.  ``weight <= 0`` short-circuits
        to the plain submit path and wraps the UNTOUCHED result
        (``source='softmax'``, bit-identical scores); no attached
        index degrades typed (``source='softmax_fallback'``) instead
        of raising.  Blended results are memoized under a key carrying
        the weight and k, refused on either a params or an index
        generation mismatch (both generations taken before the legs
        launch)."""
        from code2vec_tpu.workloads import blend as blend_lib
        if weight is None:
            weight = self.config.BLEND_NEIGHBOR_WEIGHT
        weight = float(weight)
        if not 0.0 <= weight <= 1.0:
            raise ValueError('blend weight must be in [0, 1], got %r'
                             % (weight,))
        k = k if k is not None else self.config.INDEX_NEIGHBORS_K
        t_submit0 = time.perf_counter()
        lines = canonicalize_contexts(context_lines,
                                      self.config.MAX_CONTEXTS)
        outer: Future = Future()
        if not lines:
            outer.set_result([])
            return outer
        if tele_core.enabled():
            tele_core.registry().counter(
                'mesh/blend_requests_total').inc()
        if record:
            self._record_traffic(scenario or 'retrieval_naming', lines,
                                 language=language, k=k, weight=weight)

        def _observe_outer(future: Future) -> None:
            if self._slo is None:
                return
            slo, t0, scen = self._slo, t_submit0, scenario

            def _cb(done: Future) -> None:
                try:
                    exc = done.exception()
                except BaseException:
                    return  # caller cancelled: not the server's verdict
                if exc is None:
                    slo.observe_good(time.perf_counter() - t0,
                                     scenario=scen)
                elif not isinstance(exc, EngineClosed):
                    slo.observe_bad(type(exc).__name__, scenario=scen)

            future.add_done_callback(_cb)

        def _wrap_passthrough(source: str) -> Future:
            # one warmed leg, scores passed through UNTOUCHED — the
            # weight=0 parity test asserts bit-identical arrays
            try:
                inner = self.submit(lines, tier='topk',
                                    deadline_ms=deadline_ms,
                                    scenario=scenario, record=False,
                                    observe=False)
            except EngineOverloaded:
                if self._slo is not None:
                    self._slo.observe_bad('shed', scenario=scenario)
                raise

            def _chain(done: Future) -> None:
                try:
                    rows = done.result()
                    _resolve(outer, [blend_lib.BlendResult(
                        original_name=row.original_name,
                        predicted_words=list(row.topk_predicted_words),
                        predicted_scores=row.topk_predicted_words_scores,
                        source=source, weight=weight, base=row,
                        neighbors=None) for row in rows])
                except BaseException as exc:
                    if not outer.done():
                        outer.set_exception(exc)

            inner.add_done_callback(_chain)
            _observe_outer(outer)
            return outer

        if self._index is None:
            # typed fallback, not an error: a scenario can be replayed
            # against a mesh with no index and still answer (pure
            # softmax), visibly degraded via source + counter
            if tele_core.enabled():
                tele_core.registry().counter(
                    'mesh/blend_fallback_total').inc()
            return _wrap_passthrough(blend_lib.SOURCE_FALLBACK)
        if weight <= 0.0:
            return _wrap_passthrough(blend_lib.SOURCE_SOFTMAX)
        memo = self._memo
        bkey = None
        gen = None
        igen = None
        if memo is not None:
            # keyed on weight AND k: a 0.3-blend answer must never
            # serve a 0.7-blend ask; stands down during params OR
            # index rollovers like every other memo serve path
            bkey = memo_lib.request_key(lines, 'blend@%g' % weight, k=k)
            rolling = self._rollover is not None  # graftlint: disable=lock-discipline -- benign racy read: a stale None serves one more hit, a stale rollover runs one more request live
            rolling = rolling or self._index_rollover is not None  # graftlint: disable=lock-discipline -- same benign racy read for the index-rollover axis
            cached = None if rolling else memo.lookup(bkey,
                                                      scenario=scenario)
            if cached is not None:
                if self._tracer is not None:
                    attrs = {'tier': 'blend', 'rows': len(lines),
                             'mesh': True}
                    if scenario is not None:
                        attrs['scenario'] = scenario
                    trace = self._tracer.begin('serving.request',
                                               attrs=attrs)
                    trace.event('serving.memo_hit',
                                attrs={'tier': 'blend',
                                       'rows': len(lines),
                                       'memo': 'exact'})
                    trace.finish(status='ok')
                if self._slo is not None:
                    self._slo.observe_good(
                        time.perf_counter() - t_submit0,
                        scenario=scenario)
                outer.set_result(cached)
                return outer
            # BOTH generations BEFORE the legs launch: a params or
            # index rollover concluding mid-flight makes the insert a
            # refused no-op instead of a stale cached blend
            gen = memo.generation
            igen = memo.index_generation
        try:
            base_future = self.submit(lines, tier='topk',
                                      deadline_ms=deadline_ms,
                                      scenario=scenario, record=False,
                                      observe=False)
            nbr_future = self.submit_neighbors(lines, k=k,
                                               scenario=scenario,
                                               record=False,
                                               observe=False)
        except EngineOverloaded:
            if self._slo is not None:
                self._slo.observe_bad('shed', scenario=scenario)
            raise
        state: Dict[str, object] = {}
        state_lock = threading.Lock()

        def _finish() -> None:
            try:
                base_rows = state['base']
                nbr_rows = state['nbr']
                results = [blend_lib.blend_row(
                    row, (nbr_rows[i] if i < len(nbr_rows) else None),
                    weight) for i, row in enumerate(base_rows)]
                if memo is not None:
                    memo.insert(bkey, results, gen,
                                index_generation=igen)
                _resolve(outer, results)
            except BaseException as exc:
                if not outer.done():
                    outer.set_exception(exc)

        def _arm(name: str):
            def _cb(done: Future) -> None:
                try:
                    value = done.result()
                except BaseException as exc:
                    if not outer.done():
                        outer.set_exception(exc)
                    return
                with state_lock:
                    state[name] = value
                    ready = len(state) == 2
                if ready:
                    _finish()
            return _cb

        base_future.add_done_callback(_arm('base'))
        nbr_future.add_done_callback(_arm('nbr'))
        _observe_outer(outer)
        return outer

    # --------------------------------------------------------- rollover
    def load_params(self, source, canary_batches: Optional[int] = None,
                    min_agreement: Optional[float] = None) -> Future:
        """Coordinated fleet rollover: canary on ONE replica (the
        engine's shadow-scoring machinery — zero new compiles), then on
        agreement fleet-swap the validated params onto every other
        replica atomically; on disagreement roll the canary back and
        leave EVERY replica serving the old params.  Returns a Future
        of the fleet report."""
        n_canary = (canary_batches if canary_batches is not None
                    else self.canary_batches)
        floor = (min_agreement if min_agreement is not None
                 else self.canary_agreement)
        handle: Future = Future()
        with self._cond:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if self._rollover is not None:
                raise RuntimeError(
                    'a fleet rollover is already in flight (replica %s); '
                    'await its handle first'
                    % self._rollover['replica'].rid)
            canary_slot = next(
                (slot for slot in self._replicas
                 if not slot.retired and not slot.dead
                 and not slot.restarting
                 and slot.breaker_state != _BREAKER_OPEN), None)
            if canary_slot is None:
                raise RuntimeError('no serving replica available to '
                                   'canary the rollover on')
            self._rollover = {'replica': canary_slot, 'handle': handle}
            canary_slot.canarying = True
        step = source if isinstance(source, int) and \
            not isinstance(source, bool) else None
        try:
            canary_handle = canary_slot.transport.load_params(
                source, n_canary, floor)
        except BaseException:
            with self._cond:
                self._rollover = None
                canary_slot.canarying = False
            raise
        self.log('mesh: rollover armed — canarying on replica %s '
                 '(%d batches, agreement floor %.2f)'
                 % (canary_slot.rid, n_canary, floor))

        def conclude(done: Future) -> None:
            swapped = 0
            try:
                report = done.result()
            except BaseException as exc:
                self._finish_rollover(canary_slot)
                if not handle.done():
                    handle.set_exception(exc)
                return
            if report.get('swapped'):
                resolved_step = (report.get('step')
                                 if report.get('step') is not None
                                 else step)
                params = getattr(
                    getattr(canary_slot.transport, 'engine', None),
                    'params', None)
                try:
                    for slot in self._replicas:
                        if slot is canary_slot or slot.retired or \
                                slot.dead or slot.restarting:
                            # a dead/restarting sibling re-adopts the
                            # fleet's current step when it rejoins (the
                            # supervisor's re-adoption leg)
                            continue
                        slot.transport.adopt(params, source,
                                             resolved_step)
                        swapped += 1
                except BaseException as exc:
                    # a sibling failed its adopt mid-fleet-swap (its
                    # worker died, its engine closed): the rollover
                    # machinery must still CONCLUDE — a swallowed
                    # done-callback exception would leave _rollover set
                    # forever, wedging every later load_params and the
                    # follow poller.  The canary (and any sibling that
                    # already adopted) serves the new params; the
                    # failed sibling is the breaker/retirement path's
                    # problem; the caller sees the partial swap typed.
                    self._finish_rollover(canary_slot)
                    self.log('mesh: fleet swap FAILED on a sibling '
                             'after the canary passed (%r); %d of %d '
                             'siblings adopted'
                             % (exc, swapped,
                                sum(1 for s in self._replicas
                                    if s is not canary_slot
                                    and not s.retired)))
                    if not handle.done():
                        handle.set_exception(exc)
                    return
                with self._cond:
                    self._params_step = (resolved_step
                                         if resolved_step is not None
                                         else self._params_step)
                if self._memo is not None:
                    # UNCONDITIONAL on swap, not keyed to step: a
                    # pytree-source swap has resolved_step=None and
                    # must still invalidate every memoized result
                    # atomically (generation bump, not per-entry
                    # eviction); a rolled-back canary never reaches
                    # here, so the cache stays warm on rollback
                    self._memo.bump_generation(resolved_step)
                self.rollover_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/rollover_total').inc()
                self.log('mesh: fleet rollover SWAPPED (step %s): '
                         'canary agreement %.3f on replica %s, %d '
                         'sibling(s) adopted'
                         % (resolved_step, report.get('agreement') or 0,
                            canary_slot.rid, swapped))
            else:
                self.rollover_rollbacks_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/rollover_rollbacks_total').inc()
                if self._tracer is not None:
                    self._tracer.dump_flight('rollover_rollback')
                self.log('mesh: fleet rollover ROLLED BACK on the '
                         'canary replica %s (%s); every replica keeps '
                         'the old params'
                         % (canary_slot.rid, report.get('reason')))
            self._finish_rollover(canary_slot)
            fleet_report = dict(report)
            fleet_report['canary_replica'] = canary_slot.rid
            fleet_report['replicas_swapped'] = (
                swapped + 1 if report.get('swapped') else 0)
            _resolve(handle, fleet_report)

        canary_handle.add_done_callback(conclude)
        return handle

    def _finish_rollover(self, canary_slot: _ReplicaSlot) -> None:
        with self._cond:
            canary_slot.canarying = False
            self._rollover = None
            self._cond.notify_all()
        self._queue.kick()

    # --------------------------------------------------- index rollover
    def rollover_index(self, candidate,
                       shadow_queries: Optional[int] = None,
                       min_agreement: Optional[float] = None) -> Future:
        """Canaried INDEX swap — the params-canary machinery
        generalized to indexes (SERVING.md rollover runbook, INDEX.md
        "Quantized tier").  The candidate index (a rebuilt, compacted,
        or re-quantized tier over the same corpus) attaches in SHADOW:
        live ``submit_neighbors`` traffic keeps being served by the
        current index while every query is replayed against the
        candidate in the aux pool and scored for top-k id agreement.
        After ``shadow_queries`` scored queries: agreement >= the floor
        swaps the candidate in atomically (new index version; the memo
        tier's index generation bumps, invalidating every cached
        neighbor result while predict entries survive); below the
        floor rolls back — the candidate never serves a single
        request.  Returns a Future of the report dict."""
        n_shadow = (int(shadow_queries) if shadow_queries is not None
                    else 32)
        floor = (float(min_agreement) if min_agreement is not None
                 else self.canary_agreement)
        if n_shadow < 1:
            raise ValueError('rollover_index needs shadow_queries >= 1 '
                             '(got %r)' % shadow_queries)
        if candidate is None or not hasattr(candidate, 'search'):
            raise ValueError('rollover_index needs a candidate index '
                             'with .search (got %r)' % (candidate,))
        handle: Future = Future()
        with self._cond:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if self._index is None:
                raise RuntimeError('no index attached — nothing to '
                                   'roll over; use attach_index for '
                                   'the first attach')
            if self._index_rollover is not None:
                raise RuntimeError('an index rollover is already in '
                                   'flight; await its handle first')
            self._index_rollover = {
                'candidate': candidate, 'handle': handle,
                'needed': n_shadow, 'floor': floor,
                'agree_sum': 0.0, 'count': 0, 'concluding': False,
            }
        self.log('mesh: index rollover armed — shadow-querying the '
                 'candidate on live traffic (%d queries, agreement '
                 'floor %.2f)' % (n_shadow, floor))
        return handle

    def _note_index_shadow(self, vectors: np.ndarray,
                           live_indices: np.ndarray, k: int) -> None:
        """One live neighbor query completed while an index rollover
        is armed: replay it against the candidate in the aux pool and
        accumulate top-k id agreement.  A no-op (one racy None read)
        when no rollover is in flight — the hot path stays lock-free."""
        if self._index_rollover is None:  # graftlint: disable=lock-discipline -- benign racy read: a just-armed rollover misses one query, a just-concluded one scores one extra no-op
            return
        with self._cond:
            state = self._index_rollover
            if state is None or state['concluding']:
                return
        vectors = np.array(vectors, np.float32)
        live_indices = np.array(live_indices)

        def shadow():
            try:
                _, cand_idx = state['candidate'].search(vectors, k)
            except BaseException as exc:
                self._conclude_index_rollover(
                    state, error=exc)
                return
            per_row: List[float] = []
            for row in range(live_indices.shape[0]):
                live = set(int(i) for i in live_indices[row] if i >= 0)
                if not live:
                    continue
                got = set(int(i) for i in cand_idx[row] if i >= 0)
                per_row.append(len(live & got) / len(live))
            with self._cond:
                if self._index_rollover is not state \
                        or state['concluding']:
                    return
                state['agree_sum'] += sum(per_row)
                state['count'] += len(per_row)
                running = (state['agree_sum'] / state['count']
                           if state['count'] else 0.0)
                done = state['count'] >= state['needed']
                if done:
                    state['concluding'] = True
            self.index_rollover_agreement.set(running)
            if tele_core.enabled():
                tele_core.registry().gauge(
                    'index/rollover_agreement').set(running)
            if done:
                self._conclude_index_rollover(state)
        self._aux_pool.submit(shadow)

    def _conclude_index_rollover(self, state: Dict[str, object],
                                 error=None) -> None:
        """Swap-or-rollback decision once the shadow sample is full (or
        the candidate errored — an index that cannot answer the shadow
        queries must never be swapped in)."""
        handle: Future = state['handle']
        with self._cond:
            if self._index_rollover is not state:
                return
            agreement = (state['agree_sum'] / state['count']
                         if state['count'] else 0.0)
            swapped = error is None and agreement >= state['floor']
            if swapped:
                self._index = state['candidate']
                self._index_version += 1
                version = self._index_version
            self._index_rollover = None
            self._cond.notify_all()
        if swapped:
            if self._memo is not None:
                # neighbor results are index-dependent: the index
                # generation bump invalidates them atomically while
                # predict entries survive (the model didn't change)
                self._memo.bump_index_generation()
            self.index_rollover_total.inc()
            if tele_core.enabled():
                tele_core.registry().counter(
                    'index/rollovers_total').inc()
            self.log('mesh: index rollover SWAPPED (version %d): '
                     'shadow agreement %.3f over %d queries'
                     % (version, agreement, state['count']))
        else:
            self.index_rollover_rollbacks_total.inc()
            if tele_core.enabled():
                tele_core.registry().counter(
                    'index/rollover_rollbacks_total').inc()
            self.log('mesh: index rollover ROLLED BACK (%s); the '
                     'serving index and every cached neighbor result '
                     'stay live'
                     % ('candidate error: %r' % error if error
                        is not None else 'shadow agreement %.3f < '
                        'floor %.2f over %d queries'
                        % (agreement, state['floor'], state['count'])))
        report = {'swapped': swapped, 'agreement': agreement,
                  'queries': state['count'],
                  'reason': ('candidate error: %r' % error
                             if error is not None else None)}
        if swapped:
            report['index_version'] = version
        if error is not None and not handle.done():
            handle.set_exception(
                error if isinstance(error, Exception)
                else RuntimeError(repr(error)))
            return
        _resolve(handle, report)

    def follow_checkpoints(self, poll_secs: Optional[float] = None
                           ) -> 'ServingMesh':
        """Fleet-level ``--serve-follow-checkpoints``: ONE poller rolls
        newer retained steps through the coordinated canary, so the
        fleet moves as a unit instead of N pollers racing."""
        if self._param_source is None:
            raise RuntimeError('follow_checkpoints needs a checkpointed '
                               'model (build the mesh via '
                               'model.serving_mesh())')
        poll = (poll_secs if poll_secs is not None
                else self.config.SERVE_FOLLOW_CHECKPOINTS_SECS)
        if poll <= 0:
            raise ValueError('follow_checkpoints needs poll_secs > 0 '
                             '(got %r)' % poll)
        with self._lock:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if self._follow_thread is not None:
                return self
            self._follow_thread = threading.Thread(
                target=self._follow_loop, args=(poll,), daemon=True,
                name='mesh-follow')
            self._follow_thread.start()
        return self

    def _follow_loop(self, poll_secs: float) -> None:
        attempted: Optional[int] = None
        while not self._follow_stop.wait(poll_secs):
            try:
                newest = self._param_source.newest_step()
                with self._cond:
                    if self._closed:
                        return
                    busy = self._rollover is not None
                    current = self._params_step
                if newest is None or busy:
                    continue
                if attempted is not None and newest <= attempted:
                    continue  # don't hot-loop a rolled-back step
                if current is not None and newest <= current:
                    continue
                self.log('mesh: follow-checkpoints found step %d; '
                         'starting coordinated rollover' % newest)
                self.load_params(newest)
                attempted = newest
            except EngineClosed:
                return
            except Exception as exc:  # poller must survive blips
                self.log('mesh: follow-checkpoints poll failed: %s'
                         % exc)

    # -------------------------------------------------------- lifecycle
    def warmup(self) -> 'ServingMesh':
        """Warm every replica's (bucket x capacity x tier) ladder.
        Thread-mode replicas share the trainer's jit caches, so replica
        2..N warm at cache-hit speed; the fleet compiles each program
        once."""
        for slot in self._replicas:
            slot.transport.warmup()
        return self

    def retire(self, replica_id: str, timeout: float = 120.0,
               reason: str = 'drain') -> None:
        """Drain one replica out of the fleet: it stops pulling, its
        in-flight batches deliver, its engine closes; the shared queue
        redirects to the remaining replicas throughout.  ``reason``
        lands in ``stats()``'s ``retired_reason`` and the
        reason-labeled ``mesh/retired_total`` (the autoscaler passes
        'autoscale'; operators get the 'drain' default)."""
        with self._cond:
            # prefer a non-retired slot: an adopted worker that died
            # and redialed leaves a retired slot with the same rid
            # behind, and retire() must drain the LIVE incarnation
            slot = next((s for s in self._replicas
                         if s.rid == replica_id and not s.retired),
                        None)
            if slot is None:
                slot = next((s for s in self._replicas
                             if s.rid == replica_id), None)
            if slot is None:
                raise ValueError('no replica %r in this mesh (%s)'
                                 % (replica_id,
                                    [s.rid for s in self._replicas]))
            if slot.retired:
                return
            slot.retired = True
            slot.retired_reason = reason
            was_dead = slot.dead
            self._cond.notify_all()
        self._note_retired(reason)
        self._queue.kick()
        if slot.thread is not None:
            slot.thread.join(timeout)
        deadline = time.perf_counter() + timeout
        with self._cond:
            while slot.inflight > 0:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.1))
        if not was_dead:
            slot.transport.close()  # a dead worker was already reaped
        self._set_serving_gauge_locked_free()
        self._set_live_gauge_locked_free()
        self.log('mesh: replica %s retired (served %d rows in %d '
                 'batches)' % (slot.rid, slot.rows_dispatched,
                               slot.batches))

    def stats(self) -> Dict[str, object]:
        with self._lock:
            rows_total = self._rows_total
            replicas = [{
                'replica': slot.rid,
                'retired': slot.retired,
                'retired_reason': slot.retired_reason,
                'adopted': slot.adopted,
                # placement view: the parent-assigned slice for spawned
                # workers, the worker's self-reported sub-mesh for
                # adopted ones — per-slice HBM attribution is this row's
                # 'devices' next to its 'worker_memory' ledger rollup
                'devices': (list(slot.device_indices)
                            if slot.device_indices else None),
                'dead': slot.dead,
                'restarts': slot.restarts,
                'breaker_state': slot.breaker_state,
                'inflight': slot.inflight,
                'worker_reported_inflight': (
                    slot.transport.heartbeat_info.get('inflight')
                    if isinstance(slot.transport, _WorkerReplica)
                    else None),
                # per-worker observability backhaul: remote HBM
                # pressure + the stitching clock, visible without
                # touching the worker's wire
                'worker_memory': (
                    dict(slot.transport.ledger_info) or None
                    if isinstance(slot.transport, _WorkerReplica)
                    else None),
                'clock_offset_ms': (
                    slot.transport.clock.offset * 1e3
                    if isinstance(slot.transport, _WorkerReplica)
                    and slot.transport.clock.samples else None),
                'batches': slot.batches,
                'rows_dispatched': slot.rows_dispatched,
                'dispatch_share': (slot.rows_dispatched / rows_total
                                   if rows_total else 0.0),
            } for slot in self._replicas]
            params_step = self._params_step
            fleet_rate = self._service_rows_per_s
            index_version = self._index_version
        out = {
            'replicas': replicas,
            'mode': self.mode,
            'requests_total': self.requests_total.snapshot(),
            'rows_dispatched': rows_total,
            'fleet_rows_per_s': fleet_rate,
            'params_step': params_step,
            'rollover_total': self.rollover_total.snapshot(),
            'rollover_rollbacks_total':
                self.rollover_rollbacks_total.snapshot(),
            'index_version': index_version,
            'index_rollover_total':
                self.index_rollover_total.snapshot(),
            'index_rollover_rollbacks_total':
                self.index_rollover_rollbacks_total.snapshot(),
            'replica_breaker_open_total':
                self.breaker_open_total.snapshot(),
            'restarts_total': self.restarts_total.snapshot(),
            'redispatched_total': self.redispatched_total.snapshot(),
            'retired_total': self.retired_total.snapshot(),
            'adopted_total': self.adopted_total.snapshot(),
            'adoption_rejected_total':
                self.adoption_rejected_total.snapshot(),
            'proto_rejected_total': (
                self._listener.rejected_total
                if self._listener is not None else 0),
            'placement': (
                {'devices_per_replica': self.devices_per_replica,
                 'slices': len(self._placement),
                 'data_axis': self.data_axis}
                if self._placement is not None else None),
            'autoscaler': (self._autoscaler.stats()
                           if self._autoscaler is not None else None),
            'heartbeat_misses_total':
                self.heartbeat_misses_total.snapshot(),
            'replicas_live': self.live_gauge.snapshot(),
            'adopted_spans_total': self.adopted_spans_total.snapshot(),
            'remote_spans_dropped_total':
                self.remote_spans_dropped_total.snapshot(),
            'worker_snapshots_total':
                self.worker_snapshots_total.snapshot(),
            'slo': (self._slo.stats()
                    if self._slo is not None else None),
            'memo': (self._memo.stats()
                     if self._memo is not None else None),
            'tracing': (self._tracer.stats()
                        if self._tracer is not None else None),
        }
        out.update(self._queue.stats())
        return out

    def replica_stats(self) -> List[Dict[str, object]]:
        """Per-replica engine stats (fill rate, latency timers, ...) —
        the per-replica device-fill column of bench_mesh.py.  A dead or
        retired replica has no wire to query: its row says so instead
        of hanging on a corpse."""
        out = []
        for slot in self._replicas:
            if slot.dead or slot.retired:
                out.append({'replica': slot.rid, 'dead': slot.dead,
                            'retired': slot.retired})
            else:
                out.append(slot.transport.stats())
        return out

    def close(self, drain: bool = False) -> None:
        """Stop the fleet.  Fail-fast (default): still-queued requests
        fail typed ``EngineClosed``; in-flight micro-batches deliver.
        ``drain=True`` serves everything admitted first.  Idempotent.

        The self-healing machinery is reaped, not leaked: the
        supervisor and liveness threads are joined, a restart in flight
        is cancelled (its half-built worker terminated — never adopted
        into a closed fleet, never double-restarted), and the socket
        listener closes so no late-dialing worker is left accepted."""
        with self._cond:
            already = self._closed
            if not already:
                self._closed = True
                self._drain = drain
            rollover = self._rollover
            self._rollover = None
            restart_pending = self._restart_pending
            self._cond.notify_all()
        self._follow_stop.set()
        self._close_event.set()
        if self._autoscaler is not None:
            # the autoscaler must stop DECIDING before the fleet it
            # reads starts tearing down
            self._autoscaler.close()
        if restart_pending is not None:
            # interrupt a supervisor blocked in wait_ready: the worker
            # cold start must not outlive (or be leaked by) the mesh
            restart_pending.cancel()
        self._queue.close(drain)
        if not drain:
            for request in self._queue.abandon():
                request.fail(EngineClosed(
                    'ServingMesh closed with the request still queued '
                    '(close(drain=True) serves the queue first)'))
        if rollover is not None:
            handle = rollover['handle']
            if isinstance(handle, Future) and not handle.done():
                try:
                    handle.set_exception(EngineClosed(
                        'ServingMesh closed mid-rollover'))
                except Exception:
                    pass
        follow = self._follow_thread
        if follow is not None:
            follow.join()
        if self._supervisor is not None:
            self._supervisor.join(timeout=60.0)
        if self._liveness_thread is not None:
            self._liveness_thread.join(timeout=60.0)
        if self._adopt_thread is not None:
            self._adopt_thread.join(timeout=60.0)
        for slot in self._replicas:
            if slot.thread is not None:
                slot.thread.join()
        for slot in self._replicas:
            if not slot.retired and not slot.dead:
                slot.transport.close()  # dead workers were reaped
        if self._listener is not None:
            self._listener.close()
        self._aux_pool.shutdown(wait=True)
        if self._memo is not None:
            self._memo.close()
        if self._tracer is not None and self._owns_tracer:
            self._tracer.close()

    def __enter__(self) -> 'ServingMesh':
        return self

    def __exit__(self, *exc) -> None:
        self.close()
