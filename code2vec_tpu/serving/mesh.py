"""Serving mesh: N ``ServingEngine`` replicas behind ONE shared front
queue, with continuous cross-tier batching, replica-aware admission,
and coordinated canaried rollover (SERVING.md "Serving mesh").

The single-engine story (PRs 4/7/8/9) ends at one replica: "heavy
traffic from millions of users" (ROADMAP north star) needs a FLEET —
the Ads-serving stack's shape (PAPERS.md, arxiv 2501.10546): many model
servers behind shared queues, params refreshed continuously under live
traffic.  This module is that shape for code2vec:

- **One shared front queue** (``serving/frontqueue.py``).  Admission —
  bound, deadline-vs-drain, degradation ladder — moves up to the fleet:
  the drain estimate is the fleet service rate (the mesh's sliding
  window over every replica's completions — numerically the sum of
  per-replica served-rows/s), and shedding/expiry are typed at the
  shared queue, so one slow replica never wedges its share of traffic.
- **Replica pullers = continuous cross-tier batching.**  Each replica
  runs one puller thread that claims work from the shared queue the
  moment the replica has a free in-flight slot: the puller picks the
  tier whose head waited longest and keeps folding newly-arriving
  compatible requests into the still-gathering micro-batch up to the
  coalescing deadline (the Ragged Paged Attention
  insert-into-the-in-flight-batch idea at request granularity), then
  packs onto the smallest covering (bucket x capacity-rung x tier)
  warm program of ITS engine.  Predict tiers and ``submit_neighbors``
  vectors traffic ride the same dispatch stream.
- **Replica-aware weighting.**  The replica table tracks per-replica
  in-flight windows, a dispatch circuit breaker (K consecutive dispatch
  failures open it; half-open probes one batch after the cooldown), and
  retirement — a breaker-open or retired replica simply stops pulling,
  and the queue redirects to its siblings instead of wedging.  A
  replica canarying a rollover pulls with a halved in-flight window
  (it still needs live traffic to conclude the canary; its shadow cost
  is off-latency by the engine's contract).
- **Coordinated rollover.**  ``load_params(step|path|pytree)`` canaries
  on ONE replica (reusing the engine's shadow-scoring machinery), then
  fleet-swaps the SAME validated params onto every other replica on
  agreement (``engine.adopt_params`` — pointer swap, zero compiles,
  one ledger entry), or rolls the canary back and leaves every replica
  serving the old params.  ``follow_checkpoints`` moves up here too:
  the fleet rolls as a unit instead of N pollers racing.

**Replica modes.**  ``MESH_REPLICAS`` in-process replica threads by
default (``MESH_REPLICA_MODE='thread'``): every replica is a
``ServingEngine`` in external-dispatch mode over the model's trainer,
so warm programs are shared through the trainer's jit caches and
replica 2..N warm for free.  ``'process'`` runs each replica as a
spawned worker process hosting its own model + engine, speaking the
same dispatch wire (tokenized ``Batch`` out, decoded results back) over
a pipe — the shape multi-host serving needs, so going distributed is a
config change, not a rewrite.  Process replicas restore params from the
model's checkpoint path (pytrees don't cross processes; checkpoint refs
do — which is also why process-mode rollover takes step/path sources
only).

Measured gate: ``benchmarks/bench_mesh.py`` (open-loop load at fixed
offered rate; p99 / shed rate / per-replica fill at 1/2/4 replicas).
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.serving import engine as engine_lib
from code2vec_tpu.serving.engine import (ServingEngine, _Request,
                                         _resolve)
from code2vec_tpu.serving.errors import (DeadlineExceeded, EngineClosed,
                                         EngineOverloaded)
from code2vec_tpu.serving.frontqueue import FrontQueue
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry import tracing as tracing_lib
from code2vec_tpu.telemetry.core import Counter, Gauge
from code2vec_tpu.training.trainer import PREDICT_TIERS

#: replica dispatch-breaker states (mirrors the extractor breaker's
#: numbering: serving/breaker_state semantics)
_BREAKER_CLOSED = 0
_BREAKER_HALF_OPEN = 1
_BREAKER_OPEN = 2


class _ReplicaSlot:
    """One row of the mesh replica table: transport + health + the
    dispatch accounting the weighting decisions read.  All mutable
    fields are guarded by the MESH's ``_cond`` lock (the replica's
    puller, the decode-completion hook, rollover, and retirement all
    touch them)."""

    __slots__ = ('rid', 'transport', 'thread', 'retired', 'inflight',
                 'rows_dispatched', 'batches', 'breaker_fails',
                 'breaker_state', 'breaker_open_until', 'canarying')

    def __init__(self, rid: str, transport):
        self.rid = rid
        self.transport = transport
        self.thread: Optional[threading.Thread] = None
        self.retired = False
        self.inflight = 0
        self.rows_dispatched = 0
        self.batches = 0
        self.breaker_fails = 0
        self.breaker_state = _BREAKER_CLOSED
        self.breaker_open_until = 0.0
        self.canarying = False


class _ThreadReplica:
    """In-process replica transport: a ``ServingEngine`` in
    external-dispatch mode, called directly."""

    mode = 'thread'

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def dispatch(self, tier: str, taken: List[_Request],
                 rows: int) -> None:
        self.engine.dispatch_external(tier, taken, rows)

    def wait_ready(self) -> None:
        pass  # in-process: constructed ready

    def warmup(self) -> None:
        self.engine.warmup()

    def load_params(self, source, canary_batches: int,
                    min_agreement: float) -> Future:
        return self.engine.load_params(source,
                                       canary_batches=canary_batches,
                                       min_agreement=min_agreement)

    def adopt(self, params, source, step: Optional[int]) -> None:
        # in-process fleet swap: the canary replica's validated pytree
        # IS the candidate — pointer swap, no restore, no new ledger
        # entry (the arrays are shared across replicas)
        self.engine.adopt_params(params, step=step)

    def stats(self) -> Dict[str, object]:
        return self.engine.stats()

    def close(self) -> None:
        self.engine.close()


class _ProcessReplica:
    """Process replica transport: a spawned worker hosting its own
    model + engine, fed tokenized ``Batch`` payloads over a pipe and
    returning decoded results — the same wire a multi-host mesh would
    speak, so scaling out is a config change.

    The parent-side receiver thread resolves in-flight dispatches and
    feeds the mesh's completion hook; the worker serves dispatches
    sequentially (its engine still decodes on its own pool)."""

    mode = 'process'

    # the pending map and the send side of the pipe are shared by the
    # puller, the receiver thread, and control calls (lock-discipline
    # rule, ANALYSIS.md):
    # graftlint: guard _ProcessReplica._pending,_control,_seq by _lock
    def __init__(self, rid: str, config_overrides: Dict[str, object],
                 on_batch_done, log, on_worker_dead=None,
                 start_timeout_s: float = 600.0):
        import multiprocessing
        self.rid = rid
        self.log = log
        self._on_batch_done = on_batch_done
        self._on_worker_dead = on_worker_dead
        self._start_timeout_s = start_timeout_s
        ctx = multiprocessing.get_context('spawn')
        self._conn, child = ctx.Pipe()
        self._proc = ctx.Process(
            target=_replica_worker_main,
            args=(rid, config_overrides, child), daemon=True)
        # spawn only: the worker's cold start (model build + warmup) is
        # the expensive part, and N replicas must pay it CONCURRENTLY —
        # the mesh constructs every transport first, then wait_ready()s
        # each, so fleet startup is ~one worker's wall clock, not N of
        # them
        self._proc.start()
        child.close()
        self._lock = threading.Lock()
        self._pending: Dict[int, Tuple[List[_Request], int]] = {}
        self._seq = 0
        self._control: Dict[int, Future] = {}
        self._receiver: Optional[threading.Thread] = None

    def wait_ready(self) -> None:
        """Block until the worker reported ready, then start the
        receiver.  Must run before the first dispatch/control call."""
        if self._receiver is not None:
            return
        if not self._conn.poll(self._start_timeout_s):
            self._proc.terminate()
            raise RuntimeError(
                'mesh replica %s worker did not come up within %.0fs'
                % (self.rid, self._start_timeout_s))
        try:
            msg = self._conn.recv()
        except (EOFError, OSError) as exc:
            # worker died before it could even report its failure
            self._proc.terminate()
            raise RuntimeError(
                'mesh replica %s worker exited during startup (%r) — '
                'check the worker log; process replicas need a '
                'checkpointed model with a retained step'
                % (self.rid, exc))
        if msg[0] == 'failed':
            self._proc.terminate()
            raise RuntimeError('mesh replica %s worker failed to '
                               'start: %s' % (self.rid, msg[1]))
        if msg[0] != 'ready':
            self._proc.terminate()
            raise RuntimeError('mesh replica %s worker failed to start: '
                               '%r' % (self.rid, msg))
        self._receiver = threading.Thread(target=self._recv_loop,
                                          daemon=True,
                                          name='mesh-recv-%s' % self.rid)
        self._receiver.start()

    def _control_call(self, kind: str, *payload,
                      timeout: Optional[float] = 600.0):
        future: Future = Future()
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._control[seq] = future
            self._conn.send((kind, seq) + payload)
        return future.result(timeout)

    def dispatch(self, tier: str, taken: List[_Request],
                 rows: int) -> None:
        batches = [request.batch for request in taken]
        try:
            with self._lock:
                seq = self._seq
                self._seq += 1
                self._pending[seq] = (taken, rows)
                self._conn.send(('dispatch', seq, tier, batches))
        except BaseException as exc:
            with self._lock:
                self._pending.pop(seq, None)
            # same contract as engine.dispatch_external: the member
            # requests FAIL TYPED here (the puller's breaker handler
            # assumes it), then the error propagates for breaker
            # accounting — a dead worker pipe must never leave caller
            # futures hanging
            failure = EngineClosed(
                'mesh replica %s wire send failed: %r' % (self.rid, exc))
            for request in taken:
                request.fail(failure)
            raise
        # the worker pops its queue-wait here, not in an engine this
        # process can see: close the span at hand-off so queue time is
        # attributed, not smeared into the trace tail
        now = time.perf_counter()
        for request in taken:
            if request.queue_span is not None:
                request.trace.end(request.queue_span, now)
                request.queue_span = None

    def _recv_loop(self) -> None:
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                # worker died: every in-flight dispatch fails typed
                with self._lock:
                    pending = list(self._pending.items())
                    self._pending.clear()
                    control = list(self._control.items())
                    self._control.clear()
                exc = EngineClosed(
                    'mesh replica %s worker exited with %d dispatch(es) '
                    'in flight' % (self.rid, len(pending)))
                for _seq, (taken, rows) in pending:
                    for request in taken:
                        request.fail(exc)
                    self._on_batch_done(self, rows, taken, False)
                for _seq, future in control:
                    if not future.done():
                        future.set_exception(exc)
                if self._on_worker_dead is not None:
                    # the worker can never come back (no respawn yet —
                    # ROADMAP item 2): the mesh retires the slot, so
                    # the breaker's half-open probe doesn't sacrifice
                    # one real micro-batch every cooldown forever
                    try:
                        self._on_worker_dead(self)
                    except Exception:
                        pass
                return
            kind, seq = msg[0], msg[1]
            if kind in ('result', 'error'):
                with self._lock:
                    entry = self._pending.pop(seq, None)
                    ctrl = self._control.pop(seq, None)
                if entry is not None:
                    taken, rows = entry
                    if kind == 'result':
                        for request, results in zip(taken, msg[2]):
                            request.deliver(results)
                            request.finish_trace()
                        self._on_batch_done(self, rows, taken, True)
                    else:
                        for request in taken:
                            request.fail(msg[2])
                        self._on_batch_done(self, rows, taken, False)
                elif ctrl is not None:
                    if kind == 'result':
                        _resolve(ctrl, msg[2])
                    elif not ctrl.done():
                        ctrl.set_exception(msg[2])
            elif kind == 'closed':
                with self._lock:
                    ctrl = self._control.pop(seq, None)
                if ctrl is not None:
                    _resolve(ctrl, None)
                return

    def warmup(self) -> None:
        pass  # the worker warms before it reports ready

    def load_params(self, source, canary_batches: int,
                    min_agreement: float) -> Future:
        """Arm a canaried rollover IN the worker; the returned future
        resolves with the report (a parent-side waiter polls — the
        canary concludes on the worker's live dispatch traffic)."""
        if not isinstance(source, (int, str)) or isinstance(source, bool):
            raise RuntimeError(
                'process-mode replicas roll over from checkpoint refs '
                '(step int or model path), not param pytrees — pytrees '
                'do not cross process (or host) boundaries')
        self._control_call('load_params', source, canary_batches,
                           min_agreement)
        handle: Future = Future()

        def wait() -> None:
            try:
                while True:
                    report = self._control_call('poll_rollover')
                    if report is not None:
                        _resolve(handle, report)
                        return
                    time.sleep(0.05)
            except BaseException as exc:
                if not handle.done():
                    handle.set_exception(exc)

        threading.Thread(target=wait, daemon=True,
                         name='mesh-canary-%s' % self.rid).start()
        return handle

    def adopt(self, params, source, step: Optional[int]) -> None:
        # cross-process fleet swap ships the checkpoint REF: the worker
        # restores it against its own abstract targets (canary already
        # validated the content on live traffic; canary_batches=0 swaps
        # without re-canarying)
        del params  # unused: pytrees do not cross the process wire
        self._control_call('load_params', source, 0, 0.0)
        while self._control_call('poll_rollover') is None:
            time.sleep(0.02)

    def stats(self) -> Dict[str, object]:
        return self._control_call('stats')

    def close(self) -> None:
        if self._receiver is None:
            # never became ready (a sibling's startup failed): nothing
            # to hand-shake with — just reap the worker
            self._proc.terminate()
            self._proc.join(timeout=30.0)
            self._conn.close()
            return
        try:
            self._control_call('close', timeout=60.0)
        except BaseException:
            pass  # a dead worker's pipe refuses the handshake: reap it
        if self._receiver is not threading.current_thread():
            # the worker-dead path closes from the receiver itself
            self._receiver.join(timeout=30.0)
        self._proc.join(timeout=60.0)
        if self._proc.is_alive():
            self._proc.terminate()
        self._conn.close()


def _replica_worker_main(rid: str, config_overrides: Dict[str, object],
                         conn) -> None:
    """Process-replica worker entry point (spawned): build the model
    from the shipped config, host one external-dispatch engine, serve
    the pipe."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    try:
        config = Config(**config_overrides)
        model = Code2VecModel(config)
        engine = ServingEngine(
            config, model.trainer, model.params, model.vocabs,
            decode_table=model._target_index_to_word,
            tiers=config.serving_warm_tiers,
            param_source=model._serving_param_source(),
            replica_id=rid, external_dispatch=True, log=config.log)
        engine.warmup()
    except BaseException as exc:
        # the parent must learn WHY this replica died, not just see an
        # EOF on the wire (a missing retained step, a model-build
        # failure, ...)
        try:
            conn.send(('failed', repr(exc)))
        except BaseException:
            pass
        raise
    rollover: Dict[str, object] = {'handle': None}
    conn.send(('ready', None))
    try:
        while True:
            msg = conn.recv()
            kind, seq = msg[0], msg[1]
            try:
                if kind == 'dispatch':
                    tier, batches = msg[2], msg[3]
                    requests = [_Request(batch, tier, future=Future())
                                for batch in batches]
                    rows = sum(request.rows for request in requests)
                    engine.dispatch_external(tier, requests, rows)
                    results = [request.future.result(timeout=600)
                               for request in requests]
                    conn.send(('result', seq, results))
                elif kind == 'load_params':
                    source, n_canary, floor = msg[2], msg[3], msg[4]
                    rollover['handle'] = engine.load_params(
                        source, canary_batches=n_canary,
                        min_agreement=floor)
                    conn.send(('result', seq, True))
                elif kind == 'poll_rollover':
                    handle = rollover['handle']
                    if handle is not None and handle.done():
                        rollover['handle'] = None
                        conn.send(('result', seq, handle.result()))
                    else:
                        conn.send(('result', seq, None))
                elif kind == 'stats':
                    conn.send(('result', seq, engine.stats()))
                elif kind == 'close':
                    engine.close()
                    conn.send(('closed', seq))
                    return
                else:
                    raise RuntimeError('unknown mesh wire message %r'
                                       % (kind,))
            except BaseException as exc:
                try:
                    conn.send(('error', seq, exc))
                except BaseException:
                    conn.send(('error', seq,
                               RuntimeError(repr(exc))))
    finally:
        engine.close()


# ----------------------------------------------------------------- mesh
class ServingMesh:
    """N serving replicas, one shared front queue.  Build via
    ``Code2VecModel.serving_mesh()``; the API mirrors the single
    engine's (``submit`` / ``predict`` / ``submit_neighbors`` /
    ``load_params`` / ``follow_checkpoints`` / ``close``)."""

    # the replica table, fleet service window, rollover slot and close
    # flags are shared by submitters, N pullers, decode-completion
    # hooks, and control calls (lock-discipline rule, ANALYSIS.md);
    # _cond wraps _lock:
    # graftlint: guard ServingMesh._closed,_drain,_rollover,_params_step,_rows_total,_service_window,_service_window_rows,_service_rows_per_s by _lock|_cond
    def __init__(self, model, replicas: Optional[int] = None,
                 tiers: Optional[Sequence[str]] = None,
                 mode: Optional[str] = None,
                 max_delay_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 queue_bound: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_secs: Optional[float] = None,
                 canary_batches: Optional[int] = None,
                 canary_agreement: Optional[float] = None,
                 params_step: Optional[int] = None,
                 tracer: Optional[tracing_lib.Tracer] = None,
                 tracing_sample_rate: Optional[float] = None,
                 log=None):
        config = model.config
        self.config = config
        self.log = log if log is not None else config.log
        n = int(replicas if replicas is not None else config.MESH_REPLICAS)
        if n < 1:
            raise ValueError('a mesh needs >= 1 replica, got %d' % n)
        self.mode = mode if mode is not None else config.MESH_REPLICA_MODE
        if self.mode not in ('thread', 'process'):
            raise ValueError("MESH_REPLICA_MODE must be 'thread' or "
                             "'process', got %r" % (self.mode,))
        tiers = tuple(tiers if tiers is not None
                      else config.serving_warm_tiers)
        for tier in tiers:
            if tier not in PREDICT_TIERS:
                raise ValueError('unknown tier %r; expected a subset of '
                                 '%s' % (tier, PREDICT_TIERS))
        self.tiers = tiers
        self.max_delay_s = (max_delay_ms if max_delay_ms is not None
                            else config.SERVING_MAX_DELAY_MS) / 1e3
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else config.SERVING_DEADLINE_MS)
        self.deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None
        self.max_inflight = max(1, int(
            max_inflight if max_inflight is not None
            else config.MESH_MAX_INFLIGHT))
        self.breaker_threshold = max(1, int(
            breaker_threshold if breaker_threshold is not None
            else config.MESH_BREAKER_THRESHOLD))
        self.breaker_cooldown_s = float(
            breaker_cooldown_secs if breaker_cooldown_secs is not None
            else config.MESH_BREAKER_COOLDOWN_SECS)
        self.canary_batches = (canary_batches
                               if canary_batches is not None
                               else config.SERVING_CANARY_BATCHES)
        self.canary_agreement = (canary_agreement
                                 if canary_agreement is not None
                                 else config.SERVING_CANARY_AGREEMENT)
        # submit-side tokenizer + ladder geometry (identical to every
        # replica's: same config, same mesh data axis — which is what
        # makes admitted results bit-identical to a single engine's)
        self._reader = PathContextReader(model.vocabs, config,
                                         EstimatorAction.Predict)
        self.data_axis = model.mesh.shape[mesh_lib.DATA_AXIS]
        self.buckets = engine_lib.batch_ladder(
            config.serving_batch_buckets, self.data_axis)
        bound = (queue_bound if queue_bound is not None
                 else config.MESH_QUEUE_BOUND)
        # auto bound scales WITH the fleet: every replica adds its share
        # of absorbable backlog
        self.queue_bound: Optional[int] = (
            None if bound < 0 else
            n * 8 * self.buckets[-1] if bound == 0 else bound)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._drain = False
        self._rollover: Optional[Dict[str, object]] = None
        self._rows_total = 0
        # fleet service window: same estimator the engine runs, fed by
        # EVERY replica's completions — the fleet-wide drain rate
        self._service_rows_per_s = 0.0
        self._service_window: collections.deque = collections.deque()
        self._service_window_rows = 0
        if params_step is not None:
            self._params_step: Optional[int] = params_step
        elif model.state is not None:
            self._params_step = int(model.state.step)
        else:
            self._params_step = None
        self._param_source = model._serving_param_source()
        self._follow_thread: Optional[threading.Thread] = None
        self._follow_stop = threading.Event()
        # instruments (mesh-level; per-replica series ride the engines'
        # replica-labeled mirrors)
        self.requests_total = Counter('mesh/requests_total')
        self.rollover_total = Counter('mesh/rollover_total')
        self.rollover_rollbacks_total = Counter(
            'mesh/rollover_rollbacks_total')
        self.breaker_open_total = Counter(
            'mesh/replica_breaker_open_total')
        self.replicas_gauge = Gauge('mesh/replicas')
        self.serving_gauge = Gauge('mesh/replicas_serving')
        # tracing: ONE tracer shared with every thread-mode replica, so
        # the flight recorder and span log see the whole fleet
        rate = (tracing_sample_rate if tracing_sample_rate is not None
                else config.tracing_sample_rate)
        # same ownership rule as the engine: an injected tracer is the
        # caller's to close
        self._owns_tracer = tracer is None
        if tracer is not None:
            self._tracer: Optional[tracing_lib.Tracer] = tracer
        elif rate > 0:
            out_dir = None
            if getattr(config, 'TELEMETRY_DIR', None) or \
                    config.is_saving or config.is_loading:
                from code2vec_tpu.telemetry.stepwatch import telemetry_dir
                out_dir = telemetry_dir(config)
            self._tracer = tracing_lib.Tracer(
                out_dir, sample_rate=rate,
                slow_ms=config.TRACING_SLOW_MS,
                flight_traces=config.TRACING_FLIGHT_TRACES,
                log=self.log)
        else:
            self._tracer = None
        self._queue = FrontQueue(tiers, self.queue_bound,
                                 fleet_rate=self._fleet_rate,
                                 log=self.log)
        self._index = None
        self._aux_pool = ThreadPoolExecutor(max_workers=2,
                                            thread_name_prefix='mesh-aux')
        # ---- replica table ----
        self._replicas: List[_ReplicaSlot] = []
        try:
            for i in range(n):
                rid = 'r%d' % i
                if self.mode == 'thread':
                    engine = ServingEngine(
                        config, model.trainer, model.params, model.vocabs,
                        decode_table=model._target_index_to_word,
                        tiers=tiers,
                        deadline_ms=0.0, queue_bound=-1,
                        canary_batches=self.canary_batches,
                        canary_agreement=self.canary_agreement,
                        param_source=self._param_source,
                        params_step=self._params_step,
                        tracer=self._tracer,
                        tracing_sample_rate=(0.0 if self._tracer is None
                                             else None),
                        replica_id=rid, external_dispatch=True,
                        on_batch_done=self._on_batch_done,
                        log=self.log)
                    transport = _ThreadReplica(engine)
                else:
                    transport = _ProcessReplica(
                        rid, self._process_config_overrides(model),
                        on_batch_done=self._on_process_batch_done,
                        on_worker_dead=self._on_worker_dead,
                        log=self.log)
                self._replicas.append(_ReplicaSlot(rid, transport))
            for slot in self._replicas:
                # process workers spawned above cold-start in parallel;
                # this pass just collects their 'ready' handshakes
                slot.transport.wait_ready()
        except BaseException:
            self._queue.close()
            for slot in self._replicas:
                try:
                    slot.transport.close()
                except BaseException:
                    pass
            self._aux_pool.shutdown(wait=False)
            raise
        self.replicas_gauge.set(n)
        if tele_core.enabled():
            tele_core.registry().gauge('mesh/replicas').set(n)
        self._set_serving_gauge_locked_free()
        for slot in self._replicas:
            slot.thread = threading.Thread(
                target=self._pull_loop, args=(slot,), daemon=True,
                name='mesh-pull-%s' % slot.rid)
            slot.thread.start()

    # ------------------------------------------------- process plumbing
    def _process_config_overrides(self, model) -> Dict[str, object]:
        """The config a process replica rebuilds its model from: the
        parent's fields, pointed at the parent's checkpoint path
        (pytrees don't cross processes; params come from the store)."""
        import dataclasses
        config = model.config
        load_path = (config.MODEL_LOAD_PATH if config.is_loading
                     else config.MODEL_SAVE_PATH
                     if config.is_saving else None)
        if load_path is None:
            raise RuntimeError(
                "MESH_REPLICA_MODE='process' needs a checkpointed model "
                '(a --save or --load path with at least one retained '
                'step): worker processes restore params from the store, '
                'they cannot share the parent\'s arrays')
        overrides = {}
        for field in dataclasses.fields(type(config)):
            value = getattr(config, field.name, None)
            if isinstance(value, (bool, int, float, str, type(None))):
                overrides[field.name] = value
        overrides['MODEL_LOAD_PATH'] = load_path
        overrides['MODEL_SAVE_PATH'] = ''
        overrides['TRAIN_DATA_PATH_PREFIX'] = ''
        overrides['SERVE_FOLLOW_CHECKPOINTS_SECS'] = 0.0
        # the worker warms the MESH's resolved tiers, not whatever the
        # parent's SERVING_WARM_TIERS default says — a tier the caller
        # added (submit_neighbors' 'vectors') must be warm in every
        # replica, or its first dispatch compiles on the serving path
        overrides['SERVING_WARM_TIERS'] = ','.join(self.tiers)
        return overrides

    # ----------------------------------------------------- fleet rate
    def _fleet_rate(self) -> float:
        with self._lock:
            return self._service_rows_per_s

    def _note_service_locked(self, rows: int,
                             taken: List[_Request]) -> None:
        """The engine's windowed throughput estimator
        (engine.note_service_window), fed by EVERY replica's
        completions: the window sum over its span IS the fleet-wide
        served-rows/s the shared admission divides deadlines by."""
        oldest = (min(request.t_enqueue for request in taken)
                  if taken else None)
        self._service_window_rows, self._service_rows_per_s = \
            engine_lib.note_service_window(
                self._service_window, self._service_window_rows,
                self._service_rows_per_s, rows, oldest)

    # ------------------------------------------------ replica weighting
    def _slot_cap_locked(self, slot: _ReplicaSlot) -> int:
        """In-flight window of one replica — the dispatch weight.  A
        canarying replica is halved (still pulling: the canary needs
        live traffic), a half-open breaker probes ONE batch."""
        if slot.breaker_state == _BREAKER_HALF_OPEN:
            return 1
        if slot.canarying:
            return max(1, self.max_inflight // 2)
        return self.max_inflight

    def _slot_ready_locked(self, slot: _ReplicaSlot) -> str:
        """'ready' | 'wait' | 'exit' for one puller iteration."""
        if slot.retired:
            return 'exit'
        if self._closed and not self._drain:
            return 'exit'
        if slot.breaker_state == _BREAKER_OPEN:
            if time.perf_counter() >= slot.breaker_open_until:
                slot.breaker_state = _BREAKER_HALF_OPEN
                self.log('mesh: replica %s breaker half-open (probing '
                         'one batch)' % slot.rid)
            else:
                return 'wait'
        if slot.inflight >= self._slot_cap_locked(slot):
            return 'wait'
        return 'ready'

    def _slot_alive(self, slot: _ReplicaSlot) -> bool:
        """The queue-side claim check a puller passes to
        ``pop_coalesced``: a replica that retired or tripped its breaker
        while waiting must leave WITHOUT taking work."""
        with self._lock:
            return not (slot.retired
                        or slot.breaker_state == _BREAKER_OPEN
                        or (self._closed and not self._drain))

    def _set_serving_gauge_locked_free(self) -> None:
        # reads immutable-ish counts outside the lock on purpose: the
        # gauge is advisory, and both call paths immediately follow a
        # locked mutation
        serving = sum(1 for slot in self._replicas
                      if not slot.retired
                      and slot.breaker_state != _BREAKER_OPEN)
        self.serving_gauge.set(serving)
        if tele_core.enabled():
            tele_core.registry().gauge(
                'mesh/replicas_serving').set(serving)

    # -------------------------------------------------------- pull loop
    def _pull_loop(self, slot: _ReplicaSlot) -> None:
        while True:
            with self._cond:
                while True:
                    state = self._slot_ready_locked(slot)
                    if state == 'exit':
                        return
                    if state == 'ready':
                        break
                    # bounded wait: breaker cooldowns expire on the
                    # clock, not on a notification
                    self._cond.wait(0.05)
            popped = self._queue.pop_coalesced(
                self.buckets[-1], self.max_delay_s,
                alive=lambda: self._slot_alive(slot))
            if popped is None:
                # depth read BEFORE taking the mesh lock: pop_coalesced
                # holds the queue lock while it calls back into the
                # mesh's alive() (queue->mesh order), so the mesh lock
                # must never wait on the queue lock (AB-BA deadlock); a
                # stale depth just loops once more
                depth = self._queue.depth_rows()
                with self._lock:
                    if slot.retired or (self._closed and not self._drain):
                        return
                    if self._closed and depth == 0:
                        return
                continue
            tier, taken, rows, expired = popped
            for request in expired:
                request.fail(DeadlineExceeded(
                    'request expired after %.0fms in the mesh queue '
                    '(SLO deadline %.0fms)'
                    % (1e3 * (time.perf_counter() - request.t_enqueue),
                       1e3 * (request.t_deadline - request.t_enqueue))))
            if not taken:
                continue  # a sibling drained the tier during coalesce
            with self._cond:
                slot.inflight += 1
                probing = slot.breaker_state == _BREAKER_HALF_OPEN
            try:
                slot.transport.dispatch(tier, taken, rows)
            except BaseException as exc:
                # dispatch_external already failed the member requests
                # typed; here the BREAKER accounts the replica failure
                self._dispatch_failed(slot, rows, probing, exc)
                continue
            if self.mode == 'process':
                continue  # completion arrives via the receiver thread
            # thread transport: the engine's decode worker fires
            # _on_batch_done; nothing more to do here

    def _dispatch_failed(self, slot: _ReplicaSlot, rows: int,
                         probing: bool, exc: BaseException) -> None:
        del rows, probing
        with self._cond:
            slot.inflight -= 1
            self._breaker_failure_locked(slot)
            self._cond.notify_all()
        self._queue.kick()
        self.log('mesh: replica %s dispatch failed (%s): %d consecutive'
                 % (slot.rid, exc, slot.breaker_fails))

    def _breaker_failure_locked(self, slot: _ReplicaSlot) -> None:
        slot.breaker_fails += 1
        if slot.breaker_state == _BREAKER_HALF_OPEN or \
                slot.breaker_fails >= self.breaker_threshold:
            if slot.breaker_state != _BREAKER_OPEN:
                self.breaker_open_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/replica_breaker_open_total').inc()
                self.log('mesh: replica %s dispatch breaker OPEN for '
                         '%.0fs (%d consecutive failures); queue '
                         'redirects to the remaining replicas'
                         % (slot.rid, self.breaker_cooldown_s,
                            slot.breaker_fails))
            slot.breaker_state = _BREAKER_OPEN
            slot.breaker_open_until = (time.perf_counter()
                                       + self.breaker_cooldown_s)
        self._set_serving_gauge_locked_free()

    def _on_batch_done(self, engine, rows: int, taken: List[_Request],
                       ok: bool) -> None:
        """Thread-mode completion hook (runs on the replica engine's
        decode worker)."""
        slot = next(s for s in self._replicas
                    if isinstance(s.transport, _ThreadReplica)
                    and s.transport.engine is engine)
        self._complete(slot, rows, taken, ok)

    def _on_process_batch_done(self, transport, rows: int,
                               taken: List[_Request], ok: bool) -> None:
        slot = next(s for s in self._replicas
                    if s.transport is transport)
        self._complete(slot, rows, taken, ok)

    def _on_worker_dead(self, transport) -> None:
        """A process replica's worker exited (EOF on the wire): it can
        never serve again, so retire the slot — otherwise the breaker's
        half-open probe would sacrifice one real micro-batch every
        cooldown, forever, to a corpse."""
        with self._cond:
            slot = next((s for s in self._replicas
                         if s.transport is transport), None)
            if slot is None or slot.retired:
                return
            slot.retired = True
            self._cond.notify_all()
        self._set_serving_gauge_locked_free()
        self._queue.kick()
        self.log('mesh: replica %s worker died; replica retired '
                 '(queue redirects to the remaining replicas)'
                 % slot.rid)
        try:
            transport.close()  # reap the corpse (skips the dead pipe)
        except Exception:
            pass

    def _complete(self, slot: _ReplicaSlot, rows: int,
                  taken: List[_Request], ok: bool) -> None:
        with self._cond:
            slot.inflight -= 1
            if ok:
                slot.breaker_fails = 0
                if slot.breaker_state != _BREAKER_CLOSED:
                    slot.breaker_state = _BREAKER_CLOSED
                    self.log('mesh: replica %s breaker closed (probe '
                             'succeeded)' % slot.rid)
                    self._set_serving_gauge_locked_free()
                slot.rows_dispatched += rows
                slot.batches += 1
                self._rows_total += rows
                self._note_service_locked(rows, taken)
                if tele_core.enabled() and self._rows_total > 0:
                    # per-replica dispatch share: replica-labeled series
                    # under one catalog family
                    from code2vec_tpu.telemetry import catalog
                    tele_core.registry().gauge(catalog.labeled(
                        'mesh/dispatch_share', 'replica',
                        slot.rid)).set(
                            slot.rows_dispatched / self._rows_total)
            else:
                self._breaker_failure_locked(slot)
            self._cond.notify_all()
        self._queue.kick()

    # ----------------------------------------------------------- submit
    def submit(self, context_lines: Sequence[str], tier: str = 'topk',
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one prediction request on the SHARED front queue;
        whichever free replica claims it serves it.  Same contract as
        ``ServingEngine.submit`` (typed sheds, oversize split, Future
        of one result per line)."""
        if tier not in self.tiers:
            raise ValueError('tier %r is not warmed on this mesh '
                             '(tiers=%s)' % (tier, list(self.tiers)))
        # graftlint: disable=lock-discipline -- benign racy fast-fail: a close() racing past this read is re-checked inside FrontQueue.enqueue
        if self._closed:
            raise EngineClosed('ServingMesh is closed')
        lines = list(context_lines)
        future: Future = Future()
        if not lines:
            future.set_result([])
            return future
        n = len(lines)
        if deadline_ms is None:
            deadline_s = self.deadline_s
        else:
            deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None
        self.requests_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter('mesh/requests_total').inc()
        trace = None
        if self._tracer is not None:
            trace = self._tracer.begin(
                'serving.request',
                attrs={'tier': tier, 'rows': n, 'mesh': True,
                       'deadline_ms': (1e3 * deadline_s
                                       if deadline_s else None)})
        requested_tier = tier
        t_admit0 = time.perf_counter()
        try:
            tier = self._queue.admit(n, tier, deadline_s)
        except EngineOverloaded as exc:
            if trace is not None:
                trace.event('serving.shed', attrs={'reason': str(exc)})
                trace.finish(status='shed')
                self._tracer.note_shed()
            raise
        except EngineClosed as exc:
            if trace is not None:
                trace.event('serving.closed', attrs={'reason': str(exc)})
                trace.finish(status='closed')
            raise
        t_admit1 = time.perf_counter()
        if trace is not None:
            trace.span_at('serving.admission', t_admit0, t_admit1)
            if tier != requested_tier:
                trace.event('serving.degraded',
                            attrs={'requested': requested_tier,
                                   'effective': tier})
        try:
            requests = engine_lib.tokenize_and_chunk(
                self._reader, lines, tier, future, deadline_s, trace,
                t_admit1, self.buckets[-1])
        except BaseException as exc:
            self._queue.release_reservation(n)
            if trace is not None:
                trace.finish(status='error', reason=repr(exc))
            raise
        for request in requests:
            if request.trace is not None:
                request.queue_span = request.trace.span(
                    'serving.queue_wait', parent=request.span_parent,
                    t0=request.t_enqueue)
        try:
            self._queue.enqueue(tier, requests, n)
        except EngineClosed:
            if trace is not None:
                trace.event('serving.closed',
                            attrs={'reason': 'ServingMesh is closed'})
                trace.finish(status='closed')
            raise
        return future

    def predict(self, context_lines: Sequence[str], tier: str = 'topk',
                timeout: Optional[float] = None) -> list:
        """Synchronous ``submit().result()`` convenience."""
        return self.submit(context_lines, tier).result(timeout)

    # -------------------------------------------------------- neighbors
    def attach_index(self, index) -> 'ServingMesh':
        """Arm ``submit_neighbors``: neighbor queries ride the shared
        dispatch stream's 'vectors' tier, then the attached index (one
        index serves the whole fleet — it is device-resident once)."""
        if 'vectors' not in self.tiers:
            raise ValueError(
                "submit_neighbors needs the 'vectors' tier warmed on "
                'this mesh (tiers=%s)' % list(self.tiers))
        self._index = index
        return self

    def submit_neighbors(self, context_or_vectors,
                         k: Optional[int] = None) -> Future:
        """Mesh analogue of ``ServingEngine.submit_neighbors``: context
        lines ride the micro-batched 'vectors' tier ACROSS the fleet,
        the resulting code vectors feed the shared index."""
        index = self._index
        if index is None:
            raise RuntimeError('no index attached — call '
                               'attach_index(load_index(...)) first')
        k = k if k is not None else self.config.INDEX_NEIGHBORS_K
        from code2vec_tpu.index.service import neighbors_from_search
        outer: Future = Future()
        if isinstance(context_or_vectors, np.ndarray):
            vectors = np.atleast_2d(context_or_vectors)

            def lookup():
                try:
                    values, indices = index.search(vectors, k)
                    _resolve(outer, neighbors_from_search(
                        values, indices, index.labels))
                except BaseException as exc:
                    if not outer.done():
                        outer.set_exception(exc)
            self._aux_pool.submit(lookup)
            return outer
        inner = self.submit(context_or_vectors, tier='vectors')

        def chain(done: Future) -> None:
            try:
                results = done.result()
                if not results:
                    _resolve(outer, [])
                    return
                vectors = np.stack([r.code_vector for r in results])
                values, indices = index.search(vectors, k)
                _resolve(outer, neighbors_from_search(
                    values, indices, index.labels))
            except BaseException as exc:
                if not outer.done():
                    outer.set_exception(exc)
        inner.add_done_callback(chain)
        return outer

    # --------------------------------------------------------- rollover
    def load_params(self, source, canary_batches: Optional[int] = None,
                    min_agreement: Optional[float] = None) -> Future:
        """Coordinated fleet rollover: canary on ONE replica (the
        engine's shadow-scoring machinery — zero new compiles), then on
        agreement fleet-swap the validated params onto every other
        replica atomically; on disagreement roll the canary back and
        leave EVERY replica serving the old params.  Returns a Future
        of the fleet report."""
        n_canary = (canary_batches if canary_batches is not None
                    else self.canary_batches)
        floor = (min_agreement if min_agreement is not None
                 else self.canary_agreement)
        handle: Future = Future()
        with self._cond:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if self._rollover is not None:
                raise RuntimeError(
                    'a fleet rollover is already in flight (replica %s); '
                    'await its handle first'
                    % self._rollover['replica'].rid)
            canary_slot = next(
                (slot for slot in self._replicas
                 if not slot.retired
                 and slot.breaker_state != _BREAKER_OPEN), None)
            if canary_slot is None:
                raise RuntimeError('no serving replica available to '
                                   'canary the rollover on')
            self._rollover = {'replica': canary_slot, 'handle': handle}
            canary_slot.canarying = True
        step = source if isinstance(source, int) and \
            not isinstance(source, bool) else None
        try:
            canary_handle = canary_slot.transport.load_params(
                source, n_canary, floor)
        except BaseException:
            with self._cond:
                self._rollover = None
                canary_slot.canarying = False
            raise
        self.log('mesh: rollover armed — canarying on replica %s '
                 '(%d batches, agreement floor %.2f)'
                 % (canary_slot.rid, n_canary, floor))

        def conclude(done: Future) -> None:
            swapped = 0
            try:
                report = done.result()
            except BaseException as exc:
                self._finish_rollover(canary_slot)
                if not handle.done():
                    handle.set_exception(exc)
                return
            if report.get('swapped'):
                resolved_step = (report.get('step')
                                 if report.get('step') is not None
                                 else step)
                params = getattr(
                    getattr(canary_slot.transport, 'engine', None),
                    'params', None)
                try:
                    for slot in self._replicas:
                        if slot is canary_slot or slot.retired:
                            continue
                        slot.transport.adopt(params, source,
                                             resolved_step)
                        swapped += 1
                except BaseException as exc:
                    # a sibling failed its adopt mid-fleet-swap (its
                    # worker died, its engine closed): the rollover
                    # machinery must still CONCLUDE — a swallowed
                    # done-callback exception would leave _rollover set
                    # forever, wedging every later load_params and the
                    # follow poller.  The canary (and any sibling that
                    # already adopted) serves the new params; the
                    # failed sibling is the breaker/retirement path's
                    # problem; the caller sees the partial swap typed.
                    self._finish_rollover(canary_slot)
                    self.log('mesh: fleet swap FAILED on a sibling '
                             'after the canary passed (%r); %d of %d '
                             'siblings adopted'
                             % (exc, swapped,
                                sum(1 for s in self._replicas
                                    if s is not canary_slot
                                    and not s.retired)))
                    if not handle.done():
                        handle.set_exception(exc)
                    return
                with self._cond:
                    self._params_step = (resolved_step
                                         if resolved_step is not None
                                         else self._params_step)
                self.rollover_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/rollover_total').inc()
                self.log('mesh: fleet rollover SWAPPED (step %s): '
                         'canary agreement %.3f on replica %s, %d '
                         'sibling(s) adopted'
                         % (resolved_step, report.get('agreement') or 0,
                            canary_slot.rid, swapped))
            else:
                self.rollover_rollbacks_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/rollover_rollbacks_total').inc()
                if self._tracer is not None:
                    self._tracer.dump_flight('rollover_rollback')
                self.log('mesh: fleet rollover ROLLED BACK on the '
                         'canary replica %s (%s); every replica keeps '
                         'the old params'
                         % (canary_slot.rid, report.get('reason')))
            self._finish_rollover(canary_slot)
            fleet_report = dict(report)
            fleet_report['canary_replica'] = canary_slot.rid
            fleet_report['replicas_swapped'] = (
                swapped + 1 if report.get('swapped') else 0)
            _resolve(handle, fleet_report)

        canary_handle.add_done_callback(conclude)
        return handle

    def _finish_rollover(self, canary_slot: _ReplicaSlot) -> None:
        with self._cond:
            canary_slot.canarying = False
            self._rollover = None
            self._cond.notify_all()
        self._queue.kick()

    def follow_checkpoints(self, poll_secs: Optional[float] = None
                           ) -> 'ServingMesh':
        """Fleet-level ``--serve-follow-checkpoints``: ONE poller rolls
        newer retained steps through the coordinated canary, so the
        fleet moves as a unit instead of N pollers racing."""
        if self._param_source is None:
            raise RuntimeError('follow_checkpoints needs a checkpointed '
                               'model (build the mesh via '
                               'model.serving_mesh())')
        poll = (poll_secs if poll_secs is not None
                else self.config.SERVE_FOLLOW_CHECKPOINTS_SECS)
        if poll <= 0:
            raise ValueError('follow_checkpoints needs poll_secs > 0 '
                             '(got %r)' % poll)
        with self._lock:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if self._follow_thread is not None:
                return self
            self._follow_thread = threading.Thread(
                target=self._follow_loop, args=(poll,), daemon=True,
                name='mesh-follow')
            self._follow_thread.start()
        return self

    def _follow_loop(self, poll_secs: float) -> None:
        attempted: Optional[int] = None
        while not self._follow_stop.wait(poll_secs):
            try:
                newest = self._param_source.newest_step()
                with self._cond:
                    if self._closed:
                        return
                    busy = self._rollover is not None
                    current = self._params_step
                if newest is None or busy:
                    continue
                if attempted is not None and newest <= attempted:
                    continue  # don't hot-loop a rolled-back step
                if current is not None and newest <= current:
                    continue
                self.log('mesh: follow-checkpoints found step %d; '
                         'starting coordinated rollover' % newest)
                self.load_params(newest)
                attempted = newest
            except EngineClosed:
                return
            except Exception as exc:  # poller must survive blips
                self.log('mesh: follow-checkpoints poll failed: %s'
                         % exc)

    # -------------------------------------------------------- lifecycle
    def warmup(self) -> 'ServingMesh':
        """Warm every replica's (bucket x capacity x tier) ladder.
        Thread-mode replicas share the trainer's jit caches, so replica
        2..N warm at cache-hit speed; the fleet compiles each program
        once."""
        for slot in self._replicas:
            slot.transport.warmup()
        return self

    def retire(self, replica_id: str, timeout: float = 120.0) -> None:
        """Drain one replica out of the fleet: it stops pulling, its
        in-flight batches deliver, its engine closes; the shared queue
        redirects to the remaining replicas throughout."""
        with self._cond:
            slot = next((s for s in self._replicas
                         if s.rid == replica_id), None)
            if slot is None:
                raise ValueError('no replica %r in this mesh (%s)'
                                 % (replica_id,
                                    [s.rid for s in self._replicas]))
            if slot.retired:
                return
            slot.retired = True
            self._cond.notify_all()
        self._queue.kick()
        if slot.thread is not None:
            slot.thread.join(timeout)
        deadline = time.perf_counter() + timeout
        with self._cond:
            while slot.inflight > 0:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.1))
        slot.transport.close()
        self._set_serving_gauge_locked_free()
        self.log('mesh: replica %s retired (served %d rows in %d '
                 'batches)' % (slot.rid, slot.rows_dispatched,
                               slot.batches))

    def stats(self) -> Dict[str, object]:
        with self._lock:
            rows_total = self._rows_total
            replicas = [{
                'replica': slot.rid,
                'retired': slot.retired,
                'breaker_state': slot.breaker_state,
                'inflight': slot.inflight,
                'batches': slot.batches,
                'rows_dispatched': slot.rows_dispatched,
                'dispatch_share': (slot.rows_dispatched / rows_total
                                   if rows_total else 0.0),
            } for slot in self._replicas]
            params_step = self._params_step
            fleet_rate = self._service_rows_per_s
        out = {
            'replicas': replicas,
            'mode': self.mode,
            'requests_total': self.requests_total.snapshot(),
            'rows_dispatched': rows_total,
            'fleet_rows_per_s': fleet_rate,
            'params_step': params_step,
            'rollover_total': self.rollover_total.snapshot(),
            'rollover_rollbacks_total':
                self.rollover_rollbacks_total.snapshot(),
            'replica_breaker_open_total':
                self.breaker_open_total.snapshot(),
            'tracing': (self._tracer.stats()
                        if self._tracer is not None else None),
        }
        out.update(self._queue.stats())
        return out

    def replica_stats(self) -> List[Dict[str, object]]:
        """Per-replica engine stats (fill rate, latency timers, ...) —
        the per-replica device-fill column of bench_mesh.py."""
        return [slot.transport.stats() for slot in self._replicas]

    def close(self, drain: bool = False) -> None:
        """Stop the fleet.  Fail-fast (default): still-queued requests
        fail typed ``EngineClosed``; in-flight micro-batches deliver.
        ``drain=True`` serves everything admitted first.  Idempotent."""
        with self._cond:
            already = self._closed
            if not already:
                self._closed = True
                self._drain = drain
            rollover = self._rollover
            self._rollover = None
            self._cond.notify_all()
        self._follow_stop.set()
        self._queue.close(drain)
        if not drain:
            for request in self._queue.abandon():
                request.fail(EngineClosed(
                    'ServingMesh closed with the request still queued '
                    '(close(drain=True) serves the queue first)'))
        if rollover is not None:
            handle = rollover['handle']
            if isinstance(handle, Future) and not handle.done():
                try:
                    handle.set_exception(EngineClosed(
                        'ServingMesh closed mid-rollover'))
                except Exception:
                    pass
        follow = self._follow_thread
        if follow is not None:
            follow.join()
        for slot in self._replicas:
            if slot.thread is not None:
                slot.thread.join()
        for slot in self._replicas:
            if not slot.retired:
                slot.transport.close()
        self._aux_pool.shutdown(wait=True)
        if self._tracer is not None and self._owns_tracer:
            self._tracer.close()

    def __enter__(self) -> 'ServingMesh':
        return self

    def __exit__(self, *exc) -> None:
        self.close()
