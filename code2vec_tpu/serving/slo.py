"""SLO burn-rate monitor for the serving plane (SERVING.md "SLO
burn-rate monitoring", OBSERVABILITY.md "Fleet observability").

PR 8's tail retention is passive: the span log keeps the anomalous
traces, but nobody is told WHEN the fleet starts eating its error
budget.  This module is the active alarm — the multiwindow burn-rate
pattern the Ads-serving stack (PAPERS.md) and the SRE literature use
for operating under live traffic:

- **Two SLOs.** Availability (``SERVING_SLO_AVAILABILITY``, e.g. 0.99:
  a shed, expired, or failed request burns the ``1 - target`` error
  budget) and p99 latency (``SERVING_SLO_P99_MS``: a DELIVERED request
  slower than the target burns a fixed 1% budget — the "p99" contract
  is "99% of requests under the bound").
- **Fast + slow burn windows.** The burn rate over a window is
  ``bad_fraction / budget_fraction`` — 1.0 means burning budget exactly
  as fast as the SLO allows.  An alert needs BOTH windows over
  ``SERVING_SLO_BURN_THRESHOLD``: the fast window gives detection
  latency, the slow window keeps a short blip from paging (the classic
  multiwindow multi-burn-rate rule, one threshold tier).
- **The alarm is forensics, not just a log line.** A threshold crossing
  increments ``slo/alerts_total`` and dumps the tracer's flight
  recorder to ``flight_slo_burn.jsonl`` — the last N traces, shed
  reasons and phase spans included, are on disk the moment the burn
  started, not when an operator got around to asking.  The alert
  re-arms only after the fast burn drops back under the threshold
  (latched — a sustained burn fires once, not once per request).

Fed by the serving mesh's completion stream (``ServingMesh`` wires
submit-time sheds, pop-time expiries, and per-request completions in);
the monitor itself is transport-agnostic and dependency-free, so a
bare engine or a test can drive it directly.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

from code2vec_tpu.telemetry import catalog
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter

#: latency SLOs are phrased as percentiles; p99 means 1% of requests
#: may exceed the bound — that 1% IS the latency error budget
P99_BUDGET = 0.01

#: a burn rate computed over fewer events than this is noise (one lone
#: failure at startup is a 100% bad fraction): windows below the floor
#: never alert
MIN_EVENTS = 20


#: window tallies are binned, not per-event: a 600s slow window at
#: 1k req/s would otherwise retain ~600k live tuples.  64 bins bound
#: the memory to ~65 entries per window at an eviction granularity of
#: span/64 — far finer than any sane burn threshold cares about.
_WINDOW_BINS = 64


class _Window:
    """One sliding event window with running tallies, binned by time
    bucket so memory is bounded by ``_WINDOW_BINS`` regardless of
    request rate.  Mutated only under the monitor's lock."""

    __slots__ = ('span_s', 'bin_s', 'bins', 'n', 'bad', 'slow')

    def __init__(self, span_s: float):
        self.span_s = float(span_s)
        self.bin_s = self.span_s / _WINDOW_BINS
        #: deque of [bin_start, n, bad, slow]
        self.bins: collections.deque = collections.deque()
        self.n = 0
        self.bad = 0
        self.slow = 0

    def add(self, now: float, bad: bool, slow: bool) -> None:
        start = (now // self.bin_s) * self.bin_s
        if self.bins and self.bins[-1][0] == start:
            tally = self.bins[-1]
            tally[1] += 1
            tally[2] += bad
            tally[3] += slow
        else:
            self.bins.append([start, 1, int(bad), int(slow)])
        self.n += 1
        self.bad += bad
        self.slow += slow
        self.evict(now)

    def evict(self, now: float) -> None:
        horizon = now - self.span_s
        bins = self.bins
        # a bin leaves once its whole span is past the horizon: the
        # window over-retains by at most one bin width (span/64)
        while bins and bins[0][0] + self.bin_s <= horizon:
            _start, n, bad, slow = bins.popleft()
            self.n -= n
            self.bad -= bad
            self.slow -= slow

    def burn(self, count: int, budget: float) -> float:
        if self.n == 0 or budget <= 0:
            return 0.0
        return (count / self.n) / budget


class SloMonitor:
    """Availability + p99-latency SLO burn tracking over fast/slow
    windows, with a latched flight-recorder alarm."""

    # the completion stream feeds from submitter threads, replica
    # pullers, and receiver/decode threads concurrently
    # (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard SloMonitor._fast,_slow,_alerting,_scenarios by _lock
    def __init__(self, availability: float = 0.0, p99_ms: float = 0.0,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 burn_threshold: float = 10.0,
                 min_events: int = MIN_EVENTS,
                 tracer=None, log=None):
        self.availability = float(availability)
        self.p99_s = float(p99_ms) / 1e3
        self.avail_budget = max(0.0, 1.0 - self.availability)
        self.burn_threshold = float(burn_threshold)
        self.min_events = max(1, int(min_events))
        self.tracer = tracer
        self.log = log if log is not None else (lambda msg: None)
        self._lock = threading.Lock()
        self._fast = _Window(fast_window_s)
        self._slow = _Window(slow_window_s)
        #: latched alert state per SLO key ('availability' / 'p99')
        self._alerting: Dict[str, bool] = {}
        #: scenario -> [good, bad, slow] lifetime tallies — the
        #: per-scenario error-budget burn attribution the workload
        #: replayer reads (WORKLOADS.md; scenario labels ride in from
        #: the mesh submit paths)
        self._scenarios: Dict[str, list] = {}
        self.good_total = Counter('slo/good_total')
        self.bad_total = Counter('slo/bad_total')
        self.slow_total = Counter('slo/slow_total')
        self.alerts_total = Counter('slo/alerts_total')

    @property
    def enabled(self) -> bool:
        return self.availability > 0 or self.p99_s > 0

    # ------------------------------------------------------- the stream
    def observe_good(self, latency_s: Optional[float] = None,
                     scenario: Optional[str] = None) -> None:
        """One delivered request (its latency decides the p99 leg).
        ``scenario`` attributes it to a workload (WORKLOADS.md)."""
        slow = (self.p99_s > 0 and latency_s is not None
                and latency_s > self.p99_s)
        self.good_total.inc()
        if slow:
            self.slow_total.inc()
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('slo/good_total').inc()
            if slow:
                reg.counter('slo/slow_total').inc()
            if scenario:
                reg.counter(catalog.labeled(
                    'slo/good_total', 'scenario', scenario)).inc()
                if slow:
                    reg.counter(catalog.labeled(
                        'slo/slow_total', 'scenario', scenario)).inc()
        self._observe(bad=False, slow=slow, scenario=scenario)

    def observe_bad(self, reason: str = 'failed',
                    scenario: Optional[str] = None) -> None:
        """One request the caller did NOT get an answer for — shed,
        expired, or failed typed — against the availability budget."""
        del reason  # reasons live in the trace log; the budget is one
        self.bad_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter('slo/bad_total').inc()
            if scenario:
                tele_core.registry().counter(catalog.labeled(
                    'slo/bad_total', 'scenario', scenario)).inc()
        self._observe(bad=True, slow=False, scenario=scenario)

    def _observe(self, bad: bool, slow: bool,
                 scenario: Optional[str] = None) -> None:
        now = time.monotonic()
        fired = []
        with self._lock:
            if scenario:
                tally = self._scenarios.setdefault(scenario, [0, 0, 0])
                tally[0] += not bad
                tally[1] += bad
                tally[2] += slow
            self._fast.add(now, bad, slow)
            self._slow.add(now, bad, slow)
            burns = self._burns_locked()
            for key in self._active_keys():
                fast_burn, slow_burn = burns[key]
                over = (self._fast.n >= self.min_events
                        and fast_burn > self.burn_threshold
                        and slow_burn > self.burn_threshold)
                if over and not self._alerting.get(key):
                    self._alerting[key] = True
                    fired.append((key, fast_burn, slow_burn))
                elif not over and fast_burn <= self.burn_threshold:
                    self._alerting[key] = False  # re-arm
        for key, fast_burn, slow_burn in fired:
            self._fire(key, fast_burn, slow_burn)
        self._export_burns(burns)

    def _export_burns(self, burns: Dict[str, tuple]) -> None:
        if not tele_core.enabled():
            return
        reg = tele_core.registry()
        if self.availability > 0:
            reg.gauge('slo/availability_burn_fast').set(
                burns['availability'][0])
            reg.gauge('slo/availability_burn_slow').set(
                burns['availability'][1])
        if self.p99_s > 0:
            reg.gauge('slo/p99_burn_fast').set(burns['p99'][0])
            reg.gauge('slo/p99_burn_slow').set(burns['p99'][1])

    def refresh(self) -> None:
        """Recompute (evicting) and re-export the burn gauges with NO
        new observation — wired to a periodic caller (the mesh's
        liveness tick, ``stats()`` polls) so exported burns decay to
        zero after traffic stops instead of freezing at the last
        burst's value."""
        with self._lock:
            burns = self._burns_locked()
        self._export_burns(burns)

    def burns(self) -> Dict[str, tuple]:
        """Current ``(fast_burn, slow_burn)`` per ACTIVE SLO key —
        the autoscaler's scale-up signal (serving/autoscaler.py) reads
        this directly instead of parsing ``stats()``.  Evicts at read
        time, so a burn decays after traffic stops."""
        with self._lock:
            all_burns = self._burns_locked()
            return {key: all_burns[key] for key in self._active_keys()}

    def _active_keys(self):
        if self.availability > 0:
            yield 'availability'
        if self.p99_s > 0:
            yield 'p99'

    def _burns_locked(self) -> Dict[str, tuple]:
        # evict at READ time too: with traffic stopped, a stats() call
        # an hour after a burst must report the burn as over, not
        # replay the burst-time value forever
        now = time.monotonic()
        self._fast.evict(now)
        self._slow.evict(now)
        return {
            'availability': (
                self._fast.burn(self._fast.bad, self.avail_budget),
                self._slow.burn(self._slow.bad, self.avail_budget)),
            'p99': (
                self._fast.burn(self._fast.slow, P99_BUDGET),
                self._slow.burn(self._slow.slow, P99_BUDGET)),
        }

    def _fire(self, key: str, fast_burn: float,
              slow_burn: float) -> None:
        self.alerts_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter('slo/alerts_total').inc()
        target = ('%.3f availability' % self.availability
                  if key == 'availability'
                  else 'p99 <= %.0fms' % (self.p99_s * 1e3))
        self.log('slo: %s BURN ALERT — burn rate %.1fx fast / %.1fx '
                 'slow (threshold %.1fx) against the %s SLO; flight '
                 'recorder dumping to flight_slo_burn.jsonl'
                 % (key, fast_burn, slow_burn, self.burn_threshold,
                    target))
        if self.tracer is not None:
            self.tracer.dump_flight('slo_burn')

    # ------------------------------------------------------------ report
    def stats(self) -> Dict[str, object]:
        with self._lock:
            burns = self._burns_locked()
            fast_n, slow_n = self._fast.n, self._slow.n
            alerting = dict(self._alerting)
            scenarios = {name: list(tally) for name, tally
                         in self._scenarios.items()}
        self._export_burns(burns)  # a stats poll refreshes the export
        total_bad = sum(tally[1] for tally in scenarios.values())
        total_slow = sum(tally[2] for tally in scenarios.values())
        scenario_out = {}
        for name, (good, bad, slow) in sorted(scenarios.items()):
            scenario_out[name] = {
                'good': good, 'bad': bad, 'slow': slow,
                # which workload is eating the budget: this scenario's
                # share of all scenario-attributed bad/slow events
                'availability_burn_share': (bad / total_bad
                                            if total_bad else 0.0),
                'p99_burn_share': (slow / total_slow
                                   if total_slow else 0.0),
            }
        out = {
            'availability_target': self.availability,
            'p99_target_ms': self.p99_s * 1e3,
            'burn_threshold': self.burn_threshold,
            'fast_window_events': fast_n,
            'slow_window_events': slow_n,
            'good_total': self.good_total.snapshot(),
            'bad_total': self.bad_total.snapshot(),
            'slow_total': self.slow_total.snapshot(),
            'alerts_total': self.alerts_total.snapshot(),
            # latched flags re-arm on the next OBSERVATION (a read
            # never mutates alert state); burns above are current
            'alerting': alerting,
            # per-scenario error-budget attribution (WORKLOADS.md) —
            # empty until a caller labels its submits with a scenario
            'scenarios': scenario_out,
        }
        if self.availability > 0:
            out['availability_burn_fast'] = burns['availability'][0]
            out['availability_burn_slow'] = burns['availability'][1]
        if self.p99_s > 0:
            out['p99_burn_fast'] = burns['p99'][0]
            out['p99_burn_slow'] = burns['p99'][1]
        return out
