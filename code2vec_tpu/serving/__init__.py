from code2vec_tpu.serving.extractor_bridge import Extractor
from code2vec_tpu.serving.predict import InteractivePredictor

__all__ = ['Extractor', 'InteractivePredictor']
