from code2vec_tpu.serving.errors import (DeadlineExceeded, EngineClosed,
                                         EngineOverloaded, ExtractorCrash,
                                         ExtractorError,
                                         ExtractorUnavailable,
                                         ServingError)
from code2vec_tpu.serving.extractor_bridge import Extractor, ExtractorPool
from code2vec_tpu.serving.predict import InteractivePredictor

# ServingEngine / ServingMesh / bulk_predict / export_code_vectors are
# imported from their modules directly (code2vec_tpu.serving.engine /
# .mesh / .frontqueue / .bulk): they pull in jax + the trainer, which
# the lightweight REPL pieces above must not.

__all__ = ['Extractor', 'ExtractorPool', 'InteractivePredictor',
           'ServingError', 'EngineClosed', 'EngineOverloaded',
           'DeadlineExceeded', 'ExtractorError', 'ExtractorCrash',
           'ExtractorUnavailable']
