"""The serving mesh's ONE shared front queue (SERVING.md "Serving
mesh").

A single-engine deployment queues inside the engine; a mesh of N
replicas must not — per-replica queues strand work behind a slow or
broken replica while its siblings idle.  This module is the shared
admission surface every mesh replica pulls from:

- **Fleet-wide admission.** The queue bound and the drain-estimate
  check move up from the engine: the drain rate is the FLEET service
  rate (the mesh's sliding window over every replica's completions —
  numerically the sum of per-replica served-rows/s), so a deadline is
  shed only when the whole fleet cannot meet it, not when one replica
  can't.  Shedding and deadline expiry are typed exactly like the
  engine's (``EngineOverloaded`` / ``DeadlineExceeded``) and counted
  by reason (``mesh/shed_bound_total`` / ``mesh/shed_deadline_total``).
- **Shared degradation ladder.** The same hysteresis ladder the engine
  runs (serving/engine.py ``_DEGRADE_LADDER``), driven by the SHARED
  queue's fill — under fleet-wide overload every replica serves the
  downgraded tier, instead of N ladders flapping independently.
- **Coalescing pop with continuous insert.** ``pop_coalesced`` is the
  replica puller's half of continuous cross-tier batching: it picks the
  tier whose head request has waited longest, then keeps folding
  NEWLY-ARRIVING compatible requests into the still-gathering
  micro-batch until the coalescing deadline passes or the bucket fills
  (the Ragged Paged Attention insert-into-the-in-flight-batch idea,
  applied at request granularity).  Multiple pullers pop under one
  lock, so a request is dispatched exactly once, by whichever free
  replica claims it.

Thread-safe; dependency-free above the serving engine's request types.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from code2vec_tpu.resilience import faults
from code2vec_tpu.serving.engine import (_Request, bound_rejects,
                                         overload_tier)
from code2vec_tpu.serving.errors import EngineClosed, EngineOverloaded
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter, Gauge
from code2vec_tpu.training.trainer import PREDICT_TIERS

#: pop_coalesced's idle wait quantum: state a puller waits on (breaker
#: cooldown expiry, retirement) can change without a queue notification,
#: so idle waits re-check on a bounded cadence instead of forever
_IDLE_WAIT_S = 0.05


class FrontQueue:
    """Bounded, admission-controlled request queue shared by every
    replica of one ``ServingMesh``.  Submitters admit + enqueue; replica
    pullers ``pop_coalesced``; the mesh owns close/abandon semantics."""

    # submitters, N replica pullers, and close() share the queue state
    # (lock-discipline rule, ANALYSIS.md); _cond wraps _lock, so holding
    # either alias guards the fields:
    # graftlint: guard FrontQueue._queues,_pending_rows,_reserved_rows,_closed,_drain,_overload_level,_peak_rows by _lock|_cond
    def __init__(self, tiers: Tuple[str, ...],
                 bound: Optional[int],
                 fleet_rate: Callable[[], float],
                 log=None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, collections.deque] = {
            tier: collections.deque() for tier in PREDICT_TIERS}
        self._pending_rows: Dict[str, int] = {t: 0 for t in PREDICT_TIERS}
        self._reserved_rows = 0
        self._closed = False
        self._drain = False
        self._overload_level = 0
        self._peak_rows = 0
        #: admission bound in queued rows across tiers; None = unbounded
        self.queue_bound = bound
        #: the warmed tiers — the degradation ladder never downgrades
        #: onto a cold program
        self.tiers = tiers
        #: fleet service rate in rows/s (the mesh's completion window);
        #: the fleet-wide drain estimate the deadline check divides by
        self._fleet_rate = fleet_rate
        self.log = log if log is not None else (lambda msg: None)
        # standalone instruments (mesh.stats() reads them; mirrored into
        # the process-global registry when telemetry is on)
        self.queue_depth = Gauge('mesh/queue_depth')
        self.queue_rows = Gauge('mesh/queue_rows')
        self.shed_total = Counter('mesh/shed_total')
        self.shed_bound_total = Counter('mesh/shed_bound_total')
        self.shed_deadline_total = Counter('mesh/shed_deadline_total')
        self.expired_total = Counter('mesh/expired_total')
        self.degraded_total = Counter('mesh/degraded_total')

    # ------------------------------------------------------- admission
    def _admitted_rows_locked(self) -> int:
        return sum(self._pending_rows.values()) + self._reserved_rows

    def _shed_locked(self, rows: int, why: str, reason: str) -> None:
        self.shed_total.inc()
        by_reason = {'bound': self.shed_bound_total,
                     'deadline': self.shed_deadline_total}.get(reason)
        if by_reason is not None:
            by_reason.inc()
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.counter('mesh/shed_total').inc()
            if reason == 'bound':
                reg.counter('mesh/shed_bound_total').inc()
            elif reason == 'deadline':
                reg.counter('mesh/shed_deadline_total').inc()
        raise EngineOverloaded(
            'request shed at mesh admission (%s): %d rows, %d rows '
            'queued fleet-wide, bound %s — back off and retry'
            % (why, rows, self._admitted_rows_locked(), self.queue_bound))

    def admit(self, rows: int, tier: str,
              deadline_s: Optional[float]) -> str:
        """Fleet-wide admission for one submission: shared bound check,
        FLEET drain estimate vs deadline, shared degradation ladder.
        Reserves ``rows`` against the bound (released on enqueue or
        ``release_reservation``) and returns the EFFECTIVE tier."""
        with self._cond:
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            if faults.maybe_fire('reject_all'):
                self._shed_locked(rows, 'reject_all drill', 'drill')
            admitted = self._admitted_rows_locked()
            bound = self.queue_bound
            if bound_rejects(admitted, rows, bound):
                # the engine's pile-up (not size) rule, fleet-wide
                self._shed_locked(rows, 'queue bound', 'bound')
            if deadline_s is not None:
                rate = self._fleet_rate()
                if rate > 0 and (admitted + rows) / rate > deadline_s:
                    self._shed_locked(
                        rows,
                        'fleet drain estimate %.0fms > deadline %.0fms'
                        % (1e3 * (admitted + rows) / rate,
                           1e3 * deadline_s), 'deadline')
            self._overload_level, effective = overload_tier(
                admitted, rows, bound, self._overload_level, tier,
                self.tiers)
            if effective != tier:
                self.degraded_total.inc()
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'mesh/degraded_total').inc()
            self._reserved_rows += rows
            self._peak_rows = max(self._peak_rows,
                                  self._admitted_rows_locked())
        return effective

    def release_reservation(self, rows: int) -> None:
        """Back out an admission whose tokenize/split failed before
        enqueue."""
        with self._cond:
            self._reserved_rows -= rows

    def enqueue(self, tier: str, requests: List[_Request],
                rows: int) -> None:
        """Move ``rows`` admitted rows from reservation into the queue.
        Raises ``EngineClosed`` (reservation released, nothing queued)
        when the mesh closed between admission and enqueue."""
        with self._cond:
            self._reserved_rows -= rows
            if self._closed:
                raise EngineClosed('ServingMesh is closed')
            for request in requests:
                self._queues[tier].append(request)
                self._pending_rows[tier] += request.rows
            self._set_depth_locked()
            self._cond.notify_all()

    # ------------------------------------------------------------- pop
    def _set_depth_locked(self) -> None:
        depth = sum(len(q) for q in self._queues.values())
        self.queue_depth.set(depth)
        self.queue_rows.set(sum(self._pending_rows.values()))
        if tele_core.enabled():
            reg = tele_core.registry()
            reg.gauge('mesh/queue_depth').set(depth)
            reg.gauge('mesh/queue_rows').set(
                sum(self._pending_rows.values()))

    def pop_coalesced(self, max_rows: int, max_delay_s: float,
                      alive: Callable[[], bool],
                      claim=None
                      ) -> Optional[Tuple[str, List[_Request], int,
                                          List[_Request]]]:
        """One replica puller's claim on the shared queue.

        Blocks until work exists, picks the tier whose head request has
        waited longest, then holds the gathering micro-batch open —
        folding in newly-arriving same-tier requests — until the
        coalescing deadline passes or ``max_rows`` fills (continuous
        batching's insert window).  Returns ``(tier, taken, rows,
        expired)``; ``expired`` are deadlined requests the caller must
        fail typed.  Returns ``None`` when the queue is closed and
        drained, or when ``alive()`` goes false (breaker-tripped /
        retired replicas leave WITHOUT taking work — the queue never
        wedges on a dead replica).

        ``claim`` identifies the puller's replica INCARNATION: a
        redispatched request excludes the incarnation that crashed with
        it (``_Request.exclude``), so a half-dead replica whose death
        hasn't been noticed yet can never re-claim its own crashed
        batch — skipped members stay at the queue front for a
        sibling (or the supervised restart, a NEW incarnation)."""
        with self._cond:
            while True:
                if not alive():
                    return None
                if self._closed and (not self._drain
                                     or not self._any_queued_locked()):
                    return None
                if self._any_queued_locked():
                    break
                self._cond.wait(_IDLE_WAIT_S)
            tier = min((t for t in PREDICT_TIERS if self._queues[t]),
                       key=lambda t: self._queues[t][0].t_enqueue)
            deadline = self._queues[tier][0].t_enqueue + max_delay_s
            while not self._closed:
                if not alive():
                    return None
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or \
                        self._pending_rows[tier] >= max_rows:
                    break
                self._cond.wait(min(remaining, _IDLE_WAIT_S))
            if self._closed and not self._drain:
                return None
            taken: List[_Request] = []
            expired: List[_Request] = []
            skipped: List[_Request] = []
            rows = 0
            now = time.perf_counter()
            queue = self._queues[tier]
            while queue and rows + queue[0].rows <= max_rows:
                request = queue.popleft()
                if request.t_deadline is not None \
                        and now >= request.t_deadline:
                    expired.append(request)
                    self._pending_rows[tier] -= request.rows
                    continue
                if claim is not None and request.exclude is claim:
                    skipped.append(request)
                    continue
                taken.append(request)
                rows += request.rows
            if skipped:
                # excluded members keep their place at the front
                queue.extendleft(reversed(skipped))
            self._pending_rows[tier] -= rows
            self._set_depth_locked()
        for request in expired:
            self.expired_total.inc()
            if tele_core.enabled():
                tele_core.registry().counter('mesh/expired_total').inc()
        return tier, taken, rows, expired

    def _any_queued_locked(self) -> bool:
        return any(self._queues[t] for t in PREDICT_TIERS)

    def requeue_front(self, tier: str,
                      requests: List[_Request]) -> bool:
        """Crash-safe redispatch support (serving/mesh.py): re-admit
        the members of a batch that died WITH its worker at the FRONT
        of their tier queue, original order and deadlines intact —
        already-expired members still shed typed at the next pop.  The
        mesh enforces once-only via ``_Request.redispatched``; no new
        admission check runs (the rows were already admitted and are
        re-entering, not piling on).  Returns ``False`` when the queue
        is closed fail-fast — the caller fails the requests typed
        instead of queueing work nobody will drain."""
        with self._cond:
            if self._closed and not self._drain:
                return False
            self._queues[tier].extendleft(reversed(requests))
            for request in requests:
                self._pending_rows[tier] += request.rows
            self._set_depth_locked()
            self._cond.notify_all()
        return True

    # ------------------------------------------------------- lifecycle
    def kick(self) -> None:
        """Wake every waiting puller (replica state changed: breaker,
        retirement, rollover weight)."""
        with self._cond:
            self._cond.notify_all()

    def depth_rows(self) -> int:
        with self._lock:
            return sum(self._pending_rows.values())

    def drain_seconds(self) -> Tuple[float, int, float]:
        """Estimated seconds to drain everything ADMITTED (queued +
        reserved rows) at the current fleet service rate — the
        autoscaler's queue-pressure signal (serving/autoscaler.py).
        Returns ``(drain_s, rows, rate)``; a zero rate with rows
        admitted reads as ``inf`` — a stalled fleet with backlog is
        maximal pressure, not zero."""
        with self._lock:
            rows = (sum(self._pending_rows.values())
                    + self._reserved_rows)
        rate = self._fleet_rate()
        if rows <= 0:
            return 0.0, 0, rate
        if rate <= 0:
            return float('inf'), rows, rate
        return rows / rate, rows, rate

    def peak_rows(self) -> int:
        with self._lock:
            return self._peak_rows

    def overload_level(self) -> int:
        with self._lock:
            return self._overload_level

    def close(self, drain: bool = False) -> None:
        with self._cond:
            if not self._closed:
                self._closed = True
                self._drain = drain
            self._cond.notify_all()

    def abandon(self) -> List[_Request]:
        """Fail-fast close support: drain every still-queued request for
        the caller to fail typed.  (``close(drain=True)`` instead lets
        the pullers serve the queue down.)"""
        abandoned: List[_Request] = []
        with self._cond:
            for tier in PREDICT_TIERS:
                abandoned.extend(self._queues[tier])
                self._queues[tier].clear()
                self._pending_rows[tier] = 0
            self._set_depth_locked()
            self._cond.notify_all()
        return abandoned

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                'queue_depth': self.queue_depth.snapshot(),
                'queue_rows': sum(self._pending_rows.values()),
                'queue_peak_rows': self._peak_rows,
                'queue_bound': self.queue_bound,
                'overload_level': self._overload_level,
                'shed_total': self.shed_total.snapshot(),
                'shed_bound_total': self.shed_bound_total.snapshot(),
                'shed_deadline_total':
                    self.shed_deadline_total.snapshot(),
                'expired_total': self.expired_total.snapshot(),
                'degraded_total': self.degraded_total.snapshot(),
            }
