"""Typed errors of the serving resilience layer (SERVING.md "Overload &
rollover runbook"; ROBUSTNESS.md serving pillar).

Every way a serving request can fail WITHOUT a model answer has a named
type here, so callers can route on it (shed -> retry elsewhere with
backoff; expired -> drop, the client already timed out; closed -> this
replica is going away) instead of string-matching RuntimeError text.

Hierarchy notes:

- the engine-side errors subclass ``RuntimeError``: pre-resilience
  callers that caught ``RuntimeError`` around ``submit`` keep working;
- the extractor-side errors subclass ``ValueError``: the REPL loop's
  "extraction errors are user-recoverable" contract
  (serving/predict.py) catches ``ValueError``, and these must ride that
  path — an unavailable extractor re-prompts instead of killing the
  shell.
"""
from __future__ import annotations


class ServingError(RuntimeError):
    """Base of the serving engine's typed request failures."""


class EngineClosed(ServingError):
    """The engine is shut down (or closing): the request was rejected at
    submit, or its future was failed by a non-draining ``close()``.
    Clients should fail over to another replica."""


class EngineOverloaded(ServingError):
    """Admission control shed this request: the bounded queue is full,
    the drain estimate exceeds the request's deadline, or a
    ``reject_all`` fault drill is armed. Nothing was enqueued — retry
    against another replica or with client-side backoff."""


class DeadlineExceeded(ServingError):
    """The request was admitted but its SLO deadline passed while it
    waited in the queue; it was expired instead of dispatching work the
    client has already given up on."""


class ReplicaDead(ServingError):
    """The mesh replica holding this request died (worker exit, wire
    corruption, or heartbeat-declared liveness failure) and the request
    could not be served anywhere else: it had already been redispatched
    once after a previous crash, or no serving replica remains.  A
    first crash is invisible to callers — the mesh re-admits the batch
    members at the queue front and a sibling (or the supervised
    restart) serves them."""


class AdoptionRejected(ServingError):
    """An externally-spawned worker dialed the mesh listener but failed
    adoption validation — wire-proto / batch-wire-format mismatch, a
    warm-tier ladder that does not cover the fleet's, a duplicate
    replica id, or a ready frame that never arrived within the adoption
    timeout.  The dial-in is answered with a typed ``adopt_rejected``
    frame and closed; the orchestrator that spawned the worker owns the
    retry (restart supervision for external workers is explicitly NOT
    the mesh's job — SERVING.md "Elastic fleet")."""


class WireError(ServingError):
    """A mesh transport frame failed validation — bad magic, truncated
    body, or CRC mismatch (the on-wire shape of a worker dying mid-
    write, or of stream corruption).  The replica behind the wire is
    failed typed and its stream abandoned; one bad frame never poisons
    the parent's receiver into misparsing every later frame."""


class ExtractorError(ValueError):
    """Base of the extractor bridge's typed failures (a ``ValueError``
    so the REPL's recoverable-error contract holds)."""


class ExtractorCrash(ExtractorError):
    """One extractor invocation failed for an infrastructure reason —
    spawn failure, nonzero/signal exit, or per-call timeout — as opposed
    to a clean "no paths in this input" outcome. Retried by
    ``ExtractorPool``; counted against its circuit breaker."""


class ExtractorUnavailable(ExtractorError):
    """The extractor circuit breaker is OPEN: recent calls crashed
    consecutively past the threshold, so the pool fails fast (no
    subprocess spawn, no timeout wait) until the cooldown elapses and a
    half-open probe succeeds."""
