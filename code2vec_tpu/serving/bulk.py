"""Corpus-scale offline serving: stream whole ``.c2v`` files through
eval-sized sharded batches.

The naive way to embed a corpus is thousands of tiny ``model.predict``
calls — one program dispatch, one h2d upload, and one d2h fetch per
handful of methods. This module instead drives the same double-buffered
device staging ring the trainer uses (``Trainer.stage_batches``: batch
k+1 uploads while batch k computes, decode of batch k-1 overlaps both)
at ``TEST_BATCH_SIZE`` granularity, through the TIERED predict programs
(training/trainer.py::PREDICT_TIERS):

- ``export_code_vectors`` runs the 'vectors' tier — the (B, V) logits
  matmul and top-k are dead-code-eliminated from the program, so
  embedding export pays for the encoder only — and writes one
  space-separated vector per kept example (the same format
  ``evaluate``'s ``--export_code_vectors`` path emits).
- ``bulk_predict`` streams prediction results (any tier) for an
  iterable of raw context lines, preserving input order and the
  predict-path contract that rows are never filtered.
"""
from __future__ import annotations

import time
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.serving.engine import decode_results
from code2vec_tpu.telemetry import core as tele_core


def _require_single_host(what: str) -> None:
    """The bulk paths are single-host offline tools: without per-process
    line striding and per-shard output files (the evaluate path's
    machinery) a multi-host run would feed every example to EVERY
    process and race them on one output file — fail loud instead."""
    import jax
    if jax.process_count() > 1:
        raise NotImplementedError(
            '%s is single-host only (runs on %d processes); use a '
            'one-process run of the loaded model, or evaluate() with '
            '--export_code_vectors for multi-host vector export.'
            % (what, jax.process_count()))


def _record_throughput(examples: int, seconds: float) -> float:
    rate = examples / max(seconds, 1e-9)
    if tele_core.enabled():
        tele_core.registry().gauge(
            'serving/bulk_examples_per_sec').set(rate)
    return rate


def iter_code_vector_batches(model, corpus_path: str,
                             with_labels: bool = False):
    """Stream a ``.c2v`` corpus through the 'vectors'-tier predict
    program and yield ``(vectors, labels)`` per batch — ``vectors`` a
    ``(n_i, D)`` float32 array of the batch's VALID rows, ``labels`` a
    matching object array of method names (or None unless
    ``with_labels``).

    ORDER GUARANTEE: concatenated across batches, row i is the i-th
    KEPT example of the corpus, in file order — rows with no valid
    context are dropped (the evaluate-path filter) and the short final
    batch's zero-weight padding rows are excluded. The index builder
    (code2vec_tpu/index/) and the ``.vectors`` text export both depend
    on this (tested in tests/test_bulk_order.py).

    Runs the same one-step pipeline as evaluate: batch k+1 is
    dispatched before batch k's outputs are fetched, so host-side
    consumption overlaps device compute."""
    _require_single_host('iter_code_vector_batches')
    config = model.config
    trainer = model.trainer
    # evaluate-action reader. Strings OFF unless labels are wanted: no
    # decode happens here, so the native tokenizer can cover the whole
    # parse; with labels, only the label string is retained (a single
    # split per line — the native path still covers the contexts)
    reader = PathContextReader(model.vocabs, config,
                               EstimatorAction.Evaluate,
                               data_path=corpus_path,
                               keep_strings=None if with_labels else False,
                               data_shards=trainer.mesh.shape[
                                   mesh_lib.DATA_AXIS])
    wire_format = reader.wire_format()
    total = 0
    t0 = time.perf_counter()

    def decode(out, batch):
        vectors = mesh_lib.local_rows(out['code_vectors'])
        valid = batch.weight > 0
        labels = (batch.label_strings[valid]
                  if with_labels and batch.label_strings is not None
                  else None)
        return np.asarray(vectors[valid], np.float32), labels

    pending = None
    for arrays, batch in trainer.stage_batches(
            reader.iter_epoch_prefetched(shuffle=False,
                                         wire_format=wire_format)):
        out = trainer.predict_step_placed(model.params, arrays,
                                          tier='vectors')
        if pending is not None:
            vectors, labels = decode(*pending)
            total += vectors.shape[0]
            yield vectors, labels
        pending = (out, batch)
    if pending is not None:
        vectors, labels = decode(*pending)
        total += vectors.shape[0]
        yield vectors, labels
    _record_throughput(total, time.perf_counter() - t0)


def export_code_vectors(model, corpus_path: str,
                        output_path: Optional[str] = None,
                        dtype: Optional[str] = None) -> Tuple[int, str]:
    """Embed every (valid) example of a ``.c2v`` corpus into
    ``output_path`` (default ``<corpus>.vectors``), one space-separated
    code vector per line, in corpus order (the
    ``iter_code_vector_batches`` order guarantee).

    ``dtype`` (default ``Config.VECTORS_DTYPE``) narrows the exported
    values: 'float16' halves the text footprint (fewer significant
    digits) and matches the storage dtype an index built from this file
    would use. Returns ``(n_vectors, output_path)``."""
    config = model.config
    out_path = output_path if output_path is not None \
        else corpus_path + '.vectors'
    out_dtype = np.dtype(dtype if dtype is not None
                         else getattr(config, 'VECTORS_DTYPE', 'float32'))
    total = 0
    t0 = time.perf_counter()
    with open(out_path, 'w') as out_file:
        for vectors, _labels in iter_code_vector_batches(model,
                                                         corpus_path):
            for vec in vectors.astype(out_dtype):
                out_file.write(' '.join(map(str, vec)) + '\n')
            total += vectors.shape[0]
    rate = _record_throughput(total, time.perf_counter() - t0)
    model.log('Exported %d code vectors (%s) to `%s` (%d examples/sec).'
              % (total, out_dtype.name, out_path, int(rate)))
    return total, out_path


def bulk_predict(model, context_lines: Iterable[str], tier: str = 'topk',
                 batch_size: Optional[int] = None) -> Iterator[list]:
    """Stream predictions for raw context lines (predict semantics —
    never filtered) through eval-sized warm batches, yielding one
    ``ModelPredictionResults`` per input line, in order.

    ``tier`` selects the output tier ('topk' | 'attention' | 'full' |
    'vectors'); ``batch_size`` defaults to ``TEST_BATCH_SIZE``."""
    _require_single_host('bulk_predict')
    import jax

    from code2vec_tpu.data import packed as packed_lib
    config = model.config
    trainer = model.trainer
    reader = PathContextReader(model.vocabs, config,
                               EstimatorAction.Predict)
    size = batch_size if batch_size is not None else config.TEST_BATCH_SIZE
    data_axis = trainer.mesh.shape[mesh_lib.DATA_AXIS]
    size = -(-size // data_axis) * data_axis
    wire_format = config.wire_format_for(jax.process_count())

    def batches():
        chunk = []
        for line in context_lines:
            chunk.append(line)
            if len(chunk) == size:
                yield reader.process_input_rows(chunk)
                chunk = []
        if chunk:
            yield reader.pad_batch_to(
                reader.process_input_rows(chunk), size)

    def wire_batches():
        stream = batches()
        if wire_format != 'packed':
            yield from stream
            return
        # sticky capacity across the run, exactly like training's reader
        # path — one (or a few) packed step specializations per corpus
        packer = packed_lib.StickyPacker(trainer._token_pad,
                                         trainer._path_pad,
                                         data_shards=data_axis)
        for batch in stream:
            yield packer.pack_batch(batch)

    t0 = time.perf_counter()
    total = 0
    pending = None

    def decode(out, batch) -> list:
        fetched = {key: np.asarray(value) for key, value in out.items()}
        n_rows = int((batch.weight > 0).sum())
        return decode_results(fetched, batch, n_rows,
                              model._target_index_to_word)

    for arrays, batch in trainer.stage_batches(wire_batches()):
        out = trainer.predict_step_placed(model.params, arrays, tier=tier)
        if pending is not None:
            results = decode(*pending)
            total += len(results)
            yield from results
        pending = (out, batch)
    if pending is not None:
        results = decode(*pending)
        total += len(results)
        yield from results
    _record_throughput(total, time.perf_counter() - t0)
