"""code2vec_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework with the
capabilities of tech-srl/code2vec.

The reference implementation (mounted read-only at /root/reference) is a
TensorFlow-1 graph-mode / tf.keras code2vec: a neural model that embeds a code
snippet as a bag of AST path-contexts, aggregates them with single-query soft
attention into a fixed-size code vector, and predicts the method name from it.

This package is a ground-up redesign for TPU:

- strings never touch the device: tokenization happens in the host input
  pipeline (``code2vec_tpu.data``), the model consumes int32 arrays + float
  masks (reference did in-graph ``tf.lookup.StaticHashTable`` lookups,
  vocabularies.py:108-139);
- one pure ``apply`` with flags instead of three separate graphs (reference:
  tensorflow_model.py:197-234 / 267-309);
- static shapes everywhere: invalid rows become zero-weight examples instead
  of dynamically filtered rows (reference: path_context_reader.py:153-177);
- sharding is config, not code: embedding tables / softmax get
  ``PartitionSpec``s over a ``jax.sharding.Mesh`` (``code2vec_tpu.parallel``).
"""

from code2vec_tpu.config import Config
from code2vec_tpu.vocab import Vocab, Code2VecVocabs, VocabType, SpecialWords

__version__ = '0.1.0'

__all__ = [
    'Config', 'Code2VecModel',
    'Vocab', 'Code2VecVocabs', 'VocabType', 'SpecialWords',
    '__version__',
]


def __getattr__(name):
    # lazy: importing the model pulls in jax; keep bare package import light
    if name == 'Code2VecModel':
        from code2vec_tpu.model_api import Code2VecModel
        return Code2VecModel
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
