"""flax.linen backend: the code2vec model as an ``nn.Module``.

One of the two swappable backends (the reference similarly shipped a TF1
graph backend and a tf.keras backend, selected at runtime by ``--framework``,
code2vec.py:7-13). The module owns parameter definition/initialization only;
the math is delegated to :mod:`code2vec_tpu.models.functional` so both
backends share one implementation — and unlike the reference
(README.md:210), checkpoints ARE cross-compatible because the parameter
pytrees are structurally identical.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from code2vec_tpu.models import functional


class Code2VecModule(nn.Module):
    token_vocab_size: int
    path_vocab_size: int
    target_vocab_size: int
    token_dim: int = 128
    path_dim: int = 128
    code_dim: int = 384
    dropout_keep_rate: float = 0.75
    compute_dtype: jnp.dtype = jnp.float32
    # true target-vocab size when target_vocab_size is padded for sharding
    num_valid_targets: Optional[int] = None
    # route the deterministic forward through the fused Pallas kernel
    use_pallas: bool = False

    def _params(self) -> functional.Code2VecParams:
        fan_out_uniform = jax.nn.initializers.variance_scaling(
            1.0, 'fan_out', 'uniform')
        glorot = jax.nn.initializers.glorot_uniform()
        context_dim = 2 * self.token_dim + self.path_dim
        return functional.Code2VecParams(
            token_embedding=self.param(
                'token_embedding', fan_out_uniform,
                (self.token_vocab_size, self.token_dim), jnp.float32),
            path_embedding=self.param(
                'path_embedding', fan_out_uniform,
                (self.path_vocab_size, self.path_dim), jnp.float32),
            target_embedding=self.param(
                'target_embedding', fan_out_uniform,
                (self.target_vocab_size, self.code_dim), jnp.float32),
            transform=self.param(
                'transform', glorot, (context_dim, self.code_dim),
                jnp.float32),
            attention=self.param(
                'attention', glorot, (self.code_dim, 1), jnp.float32),
        )

    @nn.compact
    def __call__(self, source, path, target, mask, *,
                 deterministic: bool = True):
        """Returns (code_vectors, attention_weights, logits)."""
        params = self._params()
        dropout_rng: Optional[jax.Array] = None
        if not deterministic and self.dropout_keep_rate < 1.0:
            dropout_rng = self.make_rng('dropout')
        code_vectors, attention_weights = functional.encode(
            params, source, path, target, mask, dropout_rng=dropout_rng,
            dropout_keep_rate=self.dropout_keep_rate,
            dtype=self.compute_dtype,
            use_pallas=self.use_pallas and deterministic)
        logits = functional.compute_logits(
            params, code_vectors, dtype=self.compute_dtype,
            num_valid_targets=self.num_valid_targets)
        return code_vectors, attention_weights, logits
