"""The code2vec model as pure functions over an explicit parameter pytree.

This is the single source of truth for the model math; both backends (the
raw-pytree 'jax' backend and the flax.linen module) call into it. The
reference implemented this math three times — train graph, test graph and
predict graph (tensorflow_model.py:197-234, 267-309) plus a second full copy
in Keras (keras_model.py:37-95); here it is one pure ``encode`` traced by XLA
once per entry point.

Forward pass (mirrors ``_calculate_weighted_contexts``,
tensorflow_model.py:236-265):

    ctx   = concat(tok[source], path[path], tok[target])      (B, C, 3d)
    ctx   = dropout(ctx)                                      train only
    x     = tanh(ctx @ TRANSFORM)                             (B, C, D)
    score = x @ ATTENTION + log(mask)                         (B, C)
    attn  = softmax(score, axis=contexts)
    code  = sum(attn * x, axis=contexts)                      (B, D)
    logit = code @ TARGET_EMB.T                               (B, Vy)

TPU-first details with no reference counterpart:

- optional bfloat16 compute: the gathered embeddings and both matmuls run in
  bf16 for the MXU; attention softmax and the final cross-entropy stay fp32;
- rows with zero valid contexts (static-shape padding) produce a *finite*
  uniform attention instead of NaN, and are excluded from the loss via the
  per-example ``weight`` (the reference filtered such rows dynamically,
  path_context_reader.py:153-177 — dynamic shapes don't fly under XLA).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# Floor for the additive log-mask so fully-masked rows stay finite (vs the
# reference's log(0) = -inf which NaNs an all-invalid row,
# tensorflow_model.py:257). Must be a NORMAL fp32 (XLA flushes denormals to
# zero, turning log back into -inf); log(1e-30) ~ -69, giving invalid
# contexts attention ~e-30 — zero at fp32 resolution.
_MASK_MIN = 1e-30


class Code2VecParams(NamedTuple):
    """The five trainable arrays (reference tensorflow_model.py:206-220,
    249-250). ``attention`` keeps the reference's (D, 1) shape."""
    token_embedding: jax.Array    # (Vt, d_tok)  WORDS_VOCAB
    path_embedding: jax.Array     # (Vp, d_path) PATHS_VOCAB
    target_embedding: jax.Array   # (Vy, D)      TARGET_WORDS_VOCAB
    transform: jax.Array          # (2*d_tok+d_path, D) TRANSFORM
    attention: jax.Array          # (D, 1)       ATTENTION


def param_shapes(*, token_vocab_size: int, path_vocab_size: int,
                 target_vocab_size: int, token_dim: int, path_dim: int,
                 code_dim: int) -> Code2VecParams:
    """Shapes-only pytree (for sharding specs / checkpoint restore)."""
    context_dim = 2 * token_dim + path_dim
    return Code2VecParams(
        token_embedding=jax.ShapeDtypeStruct((token_vocab_size, token_dim),
                                             jnp.float32),
        path_embedding=jax.ShapeDtypeStruct((path_vocab_size, path_dim),
                                            jnp.float32),
        target_embedding=jax.ShapeDtypeStruct((target_vocab_size, code_dim),
                                              jnp.float32),
        transform=jax.ShapeDtypeStruct((context_dim, code_dim), jnp.float32),
        attention=jax.ShapeDtypeStruct((code_dim, 1), jnp.float32),
    )


def init_params(rng: jax.Array, *, token_vocab_size: int,
                path_vocab_size: int, target_vocab_size: int,
                token_dim: int, path_dim: int, code_dim: int
                ) -> Code2VecParams:
    """Reference initialization: embeddings use
    variance_scaling(1.0, fan_out, uniform) (tensorflow_model.py:209-220);
    TRANSFORM and ATTENTION use TF1's default glorot_uniform (:214-216,
    249-250)."""
    k_tok, k_path, k_tgt, k_trans, k_attn = jax.random.split(rng, 5)
    context_dim = 2 * token_dim + path_dim
    fan_out_uniform = jax.nn.initializers.variance_scaling(
        1.0, 'fan_out', 'uniform')
    glorot = jax.nn.initializers.glorot_uniform()
    return Code2VecParams(
        token_embedding=fan_out_uniform(
            k_tok, (token_vocab_size, token_dim), jnp.float32),
        path_embedding=fan_out_uniform(
            k_path, (path_vocab_size, path_dim), jnp.float32),
        target_embedding=fan_out_uniform(
            k_tgt, (target_vocab_size, code_dim), jnp.float32),
        transform=glorot(k_trans, (context_dim, code_dim), jnp.float32),
        attention=glorot(k_attn, (code_dim, 1), jnp.float32),
    )


def dropout_keep_mask(dropout_rng: jax.Array, keep_rate: float, shape,
                      prng_impl: str) -> jax.Array:
    """Bernoulli keep mask for inverted dropout — THE single definition
    of the PRNG routing shared by the dense encode below and the ragged
    packed encoder (ops/pallas_ragged.py). ``prng_impl='rbg'`` rewraps
    onto the hardware RngBitGenerator: the incoming (checkpoint-portable)
    threefry key seeds 4 words of rbg state, so the big mask draw costs
    hardware RNG throughput instead of per-element threefry rounds."""
    if prng_impl == 'rbg':
        dropout_rng = jax.random.wrap_key_data(
            jax.random.bits(dropout_rng, (4,), jnp.uint32), impl='rbg')
    return jax.random.bernoulli(dropout_rng, keep_rate, shape)


def encode(params: Code2VecParams, source: jax.Array, path: jax.Array,
           target: jax.Array, mask: jax.Array, *,
           dropout_rng: Optional[jax.Array] = None,
           dropout_keep_rate: float = 1.0,
           dropout_prng_impl: str = 'threefry2x32',
           dtype: jnp.dtype = jnp.float32,
           use_pallas: bool = False,
           embed_grad_impl: str = 'dense'
           ) -> Tuple[jax.Array, jax.Array]:
    """Bag-of-contexts → (code_vectors (B, D) fp32, attention (B, C) fp32).

    ``dtype`` is the MXU compute dtype; attention softmax runs fp32.
    Dropout is applied iff ``dropout_rng`` is given and keep < 1
    (reference applies it only in the train graph,
    tensorflow_model.py:245-246). ``use_pallas`` routes the deterministic
    forward through the experimental fused kernel
    (ops/pallas_encode.py); the dropout path always uses plain jnp.
    """
    # take_rows == jnp.take for the default 'dense'; other impls reshape
    # the backward scatter-add (ops/embed_grad.py, Config.EMBED_GRAD_IMPL)
    from code2vec_tpu.ops.embed_grad import take_rows
    source_embed = take_rows(params.token_embedding, source,
                             impl=embed_grad_impl).astype(dtype)  # (B, C, d)
    path_embed = take_rows(params.path_embedding, path,
                           impl=embed_grad_impl).astype(dtype)    # (B, C, d)
    target_embed = take_rows(params.token_embedding, target,
                             impl=embed_grad_impl).astype(dtype)  # (B, C, d)

    apply_dropout = dropout_rng is not None and dropout_keep_rate < 1.0
    pallas_route = False
    if use_pallas and not apply_dropout:
        from code2vec_tpu.ops import pallas_encode
        # only on a real TPU backend: off-TPU the kernel would run in the
        # (test-only) interpreter, far slower than the fused XLA path
        # below. Gate on the DEVICE platform (tpu_backend_active), not
        # jax.default_backend() — tunnel plugins register the backend
        # under another name while devices report 'tpu'.
        pallas_route = (pallas_encode.PALLAS_AVAILABLE
                        and pallas_encode.tpu_backend_active())
    if pallas_route:
        from code2vec_tpu.ops.pallas_encode import fused_context_transform
        batch, contexts = source.shape
        # inputs stay in the compute dtype (bf16 ships half the bytes into
        # VMEM); the kernel accumulates fp32 via preferred_element_type
        x_flat, scores_flat = fused_context_transform(
            source_embed.reshape(batch * contexts, -1),
            path_embed.reshape(batch * contexts, -1),
            target_embed.reshape(batch * contexts, -1),
            params.transform.astype(dtype), params.attention.astype(dtype))
        x = x_flat.reshape(batch, contexts, -1)
        scores = scores_flat.reshape(batch, contexts)
    else:
        context_embed = jnp.concatenate(
            [source_embed, path_embed, target_embed], axis=-1)  # (B, C, 3d)
        if apply_dropout:
            keep_mask = dropout_keep_mask(dropout_rng, dropout_keep_rate,
                                          context_embed.shape,
                                          dropout_prng_impl)
            context_embed = jnp.where(
                keep_mask, context_embed / dropout_keep_rate,
                jnp.zeros_like(context_embed))
        # fp32 compute asks for true-fp32 MXU passes (TPU fp32 matmuls
        # default to lower precision); bf16 uses the native fast path.
        precision = (jax.lax.Precision.HIGHEST if dtype == jnp.float32
                     else jax.lax.Precision.DEFAULT)
        x = jnp.tanh(jnp.matmul(context_embed,
                                params.transform.astype(dtype),
                                precision=precision))             # (B, C, D)
        scores = jnp.matmul(x, params.attention.astype(dtype),
                            precision=precision)[..., 0]          # (B, C)
    scores = scores.astype(jnp.float32) + jnp.log(
        jnp.maximum(mask.astype(jnp.float32), _MASK_MIN))
    attention_weights = jax.nn.softmax(scores, axis=1)            # (B, C)

    if x.dtype == jnp.float32:
        code_vectors = jnp.einsum(
            'bc,bcd->bd', attention_weights, x,
            precision=jax.lax.Precision.HIGHEST)                  # (B, D)
    else:
        # bf16 compute mode: keep the weighted sum on the MXU fast path
        # with fp32 accumulation instead of round-tripping a full
        # (B, C, D) fp32 copy of the activations through HBM (~315 MB at
        # the java14m configuration). Softmax itself stays fp32 above.
        code_vectors = jnp.einsum(
            'bc,bcd->bd', attention_weights.astype(x.dtype), x,
            preferred_element_type=jnp.float32)                   # (B, D)
    return code_vectors, attention_weights


def encode_packed(params: Code2VecParams, ctx: jax.Array, count: jax.Array,
                  *, max_contexts: int, token_pad: int, path_pad: int,
                  dropout_rng: Optional[jax.Array] = None,
                  dropout_keep_rate: float = 1.0,
                  dropout_prng_impl: str = 'threefry2x32',
                  dtype: jnp.dtype = jnp.float32,
                  embed_grad_impl: str = 'dense',
                  use_kernel: Optional[bool] = None,
                  interpret: Optional[bool] = None,
                  mesh=None) -> Tuple[jax.Array, jax.Array]:
    """``encode`` straight off the packed wire (data/packed.py): consumes
    the ``(data_shards, capacity, 3)`` triples + per-example counts and
    produces the same ``(code_vectors (B, D) fp32, attention (B, C)
    fp32)`` outputs to fp32 rounding — without ever materializing the
    ``(B, C)`` index planes or the ``(B, C, 3d)`` context embeddings the
    unpack-then-dense path pays for (ops/pallas_ragged.py; gated by
    ``Config.USE_PALLAS_RAGGED_FUSION``). On a real TPU backend the
    forward runs the fused Pallas kernel (dropout, when given, is drawn
    over the packed layout outside the kernel and applied to its
    inputs); everywhere else the differentiable jnp twin runs — never
    the interpreter. Training differentiates through
    ``loss_and_aux_packed``'s custom-VJP route, not this one."""
    from code2vec_tpu.ops import pallas_ragged
    return pallas_ragged.ragged_encode(
        params.token_embedding, params.path_embedding, params.transform,
        params.attention, ctx, count, max_contexts=max_contexts,
        token_pad=token_pad, path_pad=path_pad, dtype=dtype,
        dropout_rng=dropout_rng, dropout_keep_rate=dropout_keep_rate,
        dropout_prng_impl=dropout_prng_impl,
        embed_grad_impl=embed_grad_impl, use_kernel=use_kernel,
        interpret=interpret, mesh=mesh)


def compute_logits(params: Code2VecParams, code_vectors: jax.Array,
                   dtype: jnp.dtype = jnp.float32,
                   num_valid_targets: Optional[int] = None) -> jax.Array:
    """code vectors → target-vocab logits, fp32 out
    (reference tensorflow_model.py:226, 297).

    ``num_valid_targets``: true target-vocab size when the table is padded
    for even sharding — padded columns are masked to a large negative so
    they drop out of both the softmax partition function and top-k."""
    precision = (jax.lax.Precision.HIGHEST if dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    logits = jnp.matmul(code_vectors.astype(dtype),
                        params.target_embedding.astype(dtype).T,
                        precision=precision)
    logits = logits.astype(jnp.float32)
    padded = params.target_embedding.shape[0]
    if num_valid_targets is not None and num_valid_targets < padded:
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                       logits.ndim - 1)
        logits = jnp.where(col < num_valid_targets, logits, -1e9)
    return logits


def weighted_ce_sums(logits: jax.Array, label: jax.Array,
                     weight: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(weighted CE sum, weight sum) — the single definition of the
    cross-entropy used by both the training loss and the streaming eval
    loss (which aggregates the sums exactly across batches and hosts).

    Written as ``logsumexp(logits) - logits[label]`` rather than indexing
    into ``log_softmax(logits)``: mathematically identical, but it reduces
    to per-example scalars without materializing a second (B, target_vocab)
    fp32 array — at java14m scale that intermediate is ~1 GB of HBM
    round-trip per step."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)          # (B,)
    picked = jnp.take_along_axis(logits, label[:, None], axis=1)[:, 0]
    ce = lse - picked
    return (ce * weight).sum(), weight.sum()


def loss_and_aux(params: Code2VecParams, source: jax.Array, path: jax.Array,
                 target: jax.Array, mask: jax.Array, label: jax.Array,
                 weight: jax.Array, *,
                 dropout_rng: Optional[jax.Array] = None,
                 dropout_keep_rate: float = 1.0,
                 dropout_prng_impl: str = 'threefry2x32',
                 dtype: jnp.dtype = jnp.float32,
                 num_valid_targets: Optional[int] = None,
                 embed_grad_impl: str = 'dense',
                 use_fused_ce: bool = False,
                 fused_ce_mesh=None,
                 remat_encode: bool = False):
    """Weighted mean sparse softmax CE (reference tensorflow_model.py:226-230
    divides the CE sum by the dynamic batch size; with static shapes the
    per-example weight plays that role: padded rows have weight 0).

    ``use_fused_ce`` routes the CE through the flash-style Pallas kernel
    (ops/pallas_ce.py): no (B, V) logits in HBM, forward or backward. On a
    multi-device mesh the kernel must be shard_mapped (GSPMD would
    replicate the opaque pallas_call), so callers pass ``fused_ce_mesh``;
    a 1-device mesh or None uses the plain kernel.

    ``remat_encode`` wraps the encode block in ``jax.checkpoint``: the
    (B, C, 3d)-sized activations (gathered context embeddings, dropout
    output, tanh input) are recomputed in the backward instead of living
    in HBM across the whole loss — the classic FLOPs-for-memory trade for
    long-context (large MAX_CONTEXTS) configurations. Numerics unchanged
    (same fp ops, same dropout PRNG draws in the replay).
    """
    def _encode(params_, source_, path_, target_, mask_, rng_):
        return encode(
            params_, source_, path_, target_, mask_, dropout_rng=rng_,
            dropout_keep_rate=dropout_keep_rate,
            dropout_prng_impl=dropout_prng_impl, dtype=dtype,
            embed_grad_impl=embed_grad_impl)[0]

    if remat_encode:
        _encode = jax.checkpoint(_encode)
    code_vectors = _encode(params, source, path, target, mask, dropout_rng)
    return _loss_from_code(params, code_vectors, label, weight, dtype,
                           num_valid_targets, use_fused_ce, fused_ce_mesh)


def _loss_from_code(params, code_vectors, label, weight, dtype,
                    num_valid_targets, use_fused_ce, fused_ce_mesh):
    """The loss tail shared by the plane and packed wires: code vectors
    -> weighted-mean CE, via materialized logits or the fused CE kernel
    (the wires differ only in how ``code_vectors`` was encoded)."""
    if use_fused_ce:
        from code2vec_tpu.ops import pallas_ce
        if not pallas_ce.PALLAS_AVAILABLE:
            raise ValueError(
                'USE_PALLAS_FUSED_CE requires jax.experimental.pallas, '
                'which failed to import on this install.')
        num_valid = (num_valid_targets if num_valid_targets is not None
                     else params.target_embedding.shape[0])
        if fused_ce_mesh is not None and fused_ce_mesh.size > 1:
            ce_sum, weight_sum = pallas_ce.sharded_fused_weighted_ce_sums(
                params.target_embedding, code_vectors, label, weight,
                num_valid, fused_ce_mesh, dtype=dtype)
        else:
            ce_sum, weight_sum = pallas_ce.fused_weighted_ce_sums(
                params.target_embedding, code_vectors, label, weight,
                num_valid, dtype=dtype)
    else:
        logits = compute_logits(params, code_vectors, dtype=dtype,
                                num_valid_targets=num_valid_targets)
        ce_sum, weight_sum = weighted_ce_sums(logits, label, weight)
    loss = ce_sum / jnp.maximum(weight_sum, 1.0)
    return loss, {'code_vectors': code_vectors,
                  'num_valid': weight_sum}


def loss_and_aux_packed(params: Code2VecParams, ctx: jax.Array,
                        count: jax.Array, label: jax.Array,
                        weight: jax.Array, *,
                        max_contexts: int, token_pad: int, path_pad: int,
                        dropout_rng: Optional[jax.Array] = None,
                        dropout_keep_rate: float = 1.0,
                        dropout_prng_impl: str = 'threefry2x32',
                        dtype: jnp.dtype = jnp.float32,
                        num_valid_targets: Optional[int] = None,
                        embed_grad_impl: str = 'dense',
                        use_fused_ce: bool = False,
                        fused_ce_mesh=None,
                        remat_encode: bool = False,
                        use_ragged_kernel: Optional[bool] = False,
                        ragged_mesh=None,
                        ragged_custom_vjp: bool = True):
    """``loss_and_aux`` straight off the packed wire: the ragged fused
    encoder replaces unpack + dense encode (USE_PALLAS_RAGGED_FUSION;
    ops/pallas_ragged.py), the CE tail is shared with the plane path.

    The encode runs under :func:`pallas_ragged.ragged_encode_code`'s
    custom VJP: the backward recomputes the per-slot state off the
    packed segments instead of storing the (D, cap, 3d) gathered
    embeddings / (D, cap, D) activations as residuals, and emits the
    token/path table gradients as packed-stream scatter-adds
    (EMBED_GRAD_IMPL / lazy-Adam compatible). ``use_ragged_kernel``
    routes both passes through the Pallas pair (None = auto on TPU —
    callers gate it with Config.RAGGED_TRAIN_KERNEL pending the >=2%
    flip verdict; False = the jnp twin pair, the CPU/fallback default).
    ``max_contexts`` only shapes the attention planes the loss never
    reads; it stays in the signature so the packed twins share one call
    shape. ``ragged_custom_vjp=False`` keeps the autodiff twin — the
    residual-storing reference the tests compare against."""
    del max_contexts  # loss consumes code vectors only
    from code2vec_tpu.ops import pallas_ragged

    def _encode(params_, ctx_, count_, rng_):
        return pallas_ragged.ragged_encode_code(
            params_.token_embedding, params_.path_embedding,
            params_.transform, params_.attention, ctx_, count_,
            token_pad=token_pad, path_pad=path_pad, dropout_rng=rng_,
            dropout_keep_rate=dropout_keep_rate,
            dropout_prng_impl=dropout_prng_impl, dtype=dtype,
            embed_grad_impl=embed_grad_impl,
            use_kernel=use_ragged_kernel, mesh=ragged_mesh,
            custom_vjp=ragged_custom_vjp)

    if remat_encode:
        _encode = jax.checkpoint(_encode)
    code_vectors = _encode(params, ctx, count, dropout_rng)
    return _loss_from_code(params, code_vectors, label, weight, dtype,
                           num_valid_targets, use_fused_ce, fused_ce_mesh)
