from code2vec_tpu.models.functional import (
    Code2VecParams, init_params, encode, compute_logits, loss_and_aux,
    param_shapes)

__all__ = ['Code2VecParams', 'init_params', 'encode', 'compute_logits',
           'loss_and_aux', 'param_shapes']
