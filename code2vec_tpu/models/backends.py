"""The two swappable model backends: 'jax' (raw pytree) and 'flax' (linen).

Mirrors the reference's runtime-selected dual backends (TF1 graph vs
tf.keras, reference code2vec.py:7-13) in a TPU-native way: both call the
same pure math in :mod:`code2vec_tpu.models.functional`; they differ only in
how parameters are created and stored. The trainer and serving layers are
backend-agnostic — a backend exposes:

- ``init(rng) -> params``                   (pytree of fp32 arrays)
- ``loss_fn(params, arrays, dropout_rng, mesh=None)`` → (loss, aux)
- ``forward(params, arrays)``               → (code_vectors, attention, logits)
- ``named_params(params) -> Code2VecParams`` (for export / sharding)
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from code2vec_tpu.config import Config
from code2vec_tpu.models import functional
from code2vec_tpu.models.flax_model import Code2VecModule
from code2vec_tpu.vocab import Code2VecVocabs

# arrays order produced by Batch.device_arrays()
# (source, path, target, mask, label, weight)


def compute_dtype(config: Config) -> jnp.dtype:
    return jnp.bfloat16 if config.COMPUTE_DTYPE == 'bfloat16' else jnp.float32


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def target_row_alignment(config: Config) -> int:
    """Row alignment of the TARGET table allocation. Folds in the fused-CE
    tile so the kernel's own pad is a no-op (otherwise every step would
    physically copy the ~400 MB table to a tile multiple, twice); on a
    model-sharded mesh the kernel streams PER-SHARD rows, so the no-copy
    condition is V/model_axis % VOCAB_TILE == 0. The resulting padded row
    count is recorded in checkpoint metadata ('target_vocab_rows') since
    it determines the saved array's shape; restore ADAPTS a differing row
    count by padding/slicing the masked padding rows (checkpoints.py), so
    the allocation being topology-dependent does not make checkpoints
    topology-dependent (ADVICE r3)."""
    align = max(config.PARAM_ROW_ALIGNMENT, 1)
    if config.USE_PALLAS_FUSED_CE:
        import math

        from code2vec_tpu.ops.pallas_ce import VOCAB_TILE
        align = math.lcm(align,
                         VOCAB_TILE * max(config.MESH_MODEL_AXIS_SIZE, 1))
    return align


class JaxBackend:
    """Raw functional backend: params are a ``Code2VecParams`` NamedTuple."""

    name = 'jax'

    def __init__(self, config: Config, vocabs: Code2VecVocabs):
        self.config = config
        align = max(config.PARAM_ROW_ALIGNMENT, 1)
        # fused CE grows the target alignment to its vocab tile; padded
        # columns are masked by num_valid_targets everywhere, so only the
        # allocation grows
        target_align = target_row_alignment(config)
        # tables padded for even row-sharding over the model axis; padded
        # token/path rows are never gathered, padded target columns are
        # masked out of the softmax via num_valid_targets
        self.num_valid_targets = vocabs.target_vocab.size
        # PAD indices for the packed wire format's device-side unpack
        # (data/packed.py): must match the reader's pack-time fill.
        # SizeOnlyVocabs (benchmarks/graft) carries no pad_index — the
        # joined PAD==OOV policy puts both at 0 there.
        self.token_pad_index = getattr(vocabs.token_vocab, 'pad_index', 0)
        self.path_pad_index = getattr(vocabs.path_vocab, 'pad_index', 0)
        self.sizes = dict(
            token_vocab_size=_round_up(vocabs.token_vocab.size, align),
            path_vocab_size=_round_up(vocabs.path_vocab.size, align),
            target_vocab_size=_round_up(vocabs.target_vocab.size,
                                        target_align),
            token_dim=config.TOKEN_EMBEDDINGS_SIZE,
            path_dim=config.PATH_EMBEDDINGS_SIZE,
            code_dim=config.CODE_VECTOR_SIZE)
        self.dtype = compute_dtype(config)

    def init(self, rng: jax.Array) -> functional.Code2VecParams:
        return functional.init_params(rng, **self.sizes)

    def param_shapes(self) -> functional.Code2VecParams:
        return functional.param_shapes(**self.sizes)

    def loss_fn(self, params, arrays, dropout_rng,
                mesh=None) -> Tuple[jax.Array, Any]:
        source, path, target, mask, label, weight = arrays
        return functional.loss_and_aux(
            params, source, path, target, mask, label, weight,
            dropout_rng=dropout_rng,
            dropout_keep_rate=self.config.DROPOUT_KEEP_RATE,
            dropout_prng_impl=self.config.DROPOUT_PRNG_IMPL,
            dtype=self.dtype, num_valid_targets=self.num_valid_targets,
            embed_grad_impl=self.config.EMBED_GRAD_IMPL,
            use_fused_ce=self.config.USE_PALLAS_FUSED_CE,
            fused_ce_mesh=mesh,
            remat_encode=self.config.REMAT_ENCODE)

    def forward(self, params, arrays):
        source, path, target, mask = arrays[:4]
        code_vectors, attention = functional.encode(
            params, source, path, target, mask, dtype=self.dtype,
            use_pallas=self.config.USE_PALLAS_FUSED_ENCODE)
        logits = functional.compute_logits(
            params, code_vectors, dtype=self.dtype,
            num_valid_targets=self.num_valid_targets)
        return code_vectors, attention, logits

    def loss_fn_packed(self, params, packed_arrays, dropout_rng,
                       mesh=None) -> Tuple[jax.Array, Any]:
        """``loss_fn`` straight off the packed wire (USE_PALLAS_RAGGED_
        FUSION): the ragged fused encoder consumes the (D, cap, 3)
        triples + counts directly — no device-side unpack, no (B, C, .)
        planes — and its custom VJP recomputes the backward off the same
        segments instead of storing per-slot residuals
        (ops/pallas_ragged.py). RAGGED_TRAIN_KERNEL additionally routes
        both train passes through the Pallas kernel pair on a real TPU
        backend (None = auto there; False pins the jnp twin pair — the
        default pending the >=2% flip verdict, scripts/flip_verdict.py)."""
        ctx, count, label, weight = packed_arrays
        return functional.loss_and_aux_packed(
            params, ctx, count, label, weight,
            max_contexts=self.config.MAX_CONTEXTS,
            token_pad=self.token_pad_index,
            path_pad=self.path_pad_index,
            dropout_rng=dropout_rng,
            dropout_keep_rate=self.config.DROPOUT_KEEP_RATE,
            dropout_prng_impl=self.config.DROPOUT_PRNG_IMPL,
            dtype=self.dtype, num_valid_targets=self.num_valid_targets,
            embed_grad_impl=self.config.EMBED_GRAD_IMPL,
            use_fused_ce=self.config.USE_PALLAS_FUSED_CE,
            fused_ce_mesh=mesh,
            remat_encode=self.config.REMAT_ENCODE,
            use_ragged_kernel=(None if self.config.RAGGED_TRAIN_KERNEL
                               else False),
            ragged_mesh=mesh)

    def forward_packed(self, params, packed_arrays, mesh=None):
        """Deterministic forward off the packed wire: on a real TPU
        backend the fused Pallas kernel runs (shard_mapped over ``mesh``
        when multi-device); elsewhere the jnp twin."""
        ctx, count = packed_arrays[0], packed_arrays[1]
        code_vectors, attention = functional.encode_packed(
            params, ctx, count, max_contexts=self.config.MAX_CONTEXTS,
            token_pad=self.token_pad_index,
            path_pad=self.path_pad_index, dtype=self.dtype,
            embed_grad_impl=self.config.EMBED_GRAD_IMPL, mesh=mesh)
        logits = functional.compute_logits(
            params, code_vectors, dtype=self.dtype,
            num_valid_targets=self.num_valid_targets)
        return code_vectors, attention, logits

    def named_params(self, params) -> functional.Code2VecParams:
        return params

    def from_canonical(self, named: dict) -> functional.Code2VecParams:
        """Canonical {name: array} checkpoint layout → backend layout."""
        return functional.Code2VecParams(**named)


class FlaxBackend:
    """flax.linen backend: params are the module's ``{'params': {...}}``
    dict."""

    name = 'flax'

    def __init__(self, config: Config, vocabs: Code2VecVocabs):
        self.config = config
        self.dtype = compute_dtype(config)
        self._jax_twin = JaxBackend(config, vocabs)
        sizes = self.sizes = self._jax_twin.sizes
        self.num_valid_targets = self._jax_twin.num_valid_targets
        self.token_pad_index = self._jax_twin.token_pad_index
        self.path_pad_index = self._jax_twin.path_pad_index
        self.module = Code2VecModule(
            token_vocab_size=sizes['token_vocab_size'],
            path_vocab_size=sizes['path_vocab_size'],
            target_vocab_size=sizes['target_vocab_size'],
            token_dim=config.TOKEN_EMBEDDINGS_SIZE,
            path_dim=config.PATH_EMBEDDINGS_SIZE,
            code_dim=config.CODE_VECTOR_SIZE,
            dropout_keep_rate=config.DROPOUT_KEEP_RATE,
            compute_dtype=self.dtype,
            num_valid_targets=self.num_valid_targets,
            use_pallas=config.USE_PALLAS_FUSED_ENCODE)

    def init(self, rng: jax.Array):
        dummy = jnp.zeros((1, self.config.MAX_CONTEXTS), dtype=jnp.int32)
        dummy_mask = jnp.zeros((1, self.config.MAX_CONTEXTS),
                               dtype=jnp.float32)
        return self.module.init(rng, dummy, dummy, dummy, dummy_mask)

    def param_shapes(self):
        shapes = self._jax_twin.param_shapes()
        return {'params': shapes._asdict()}

    def loss_fn(self, params, arrays, dropout_rng,
                mesh=None) -> Tuple[jax.Array, Any]:
        # Delegate the loss math to functional via the extracted params so
        # both backends are numerically identical.
        return self._jax_twin.loss_fn(self.named_params(params), arrays,
                                      dropout_rng, mesh=mesh)

    def forward(self, params, arrays):
        source, path, target, mask = arrays[:4]
        return self.module.apply(params, source, path, target, mask,
                                 deterministic=True)

    def loss_fn_packed(self, params, packed_arrays, dropout_rng,
                       mesh=None) -> Tuple[jax.Array, Any]:
        # same delegation as loss_fn: the packed-wire math is identical
        # across backends by construction
        return self._jax_twin.loss_fn_packed(
            self.named_params(params), packed_arrays, dropout_rng,
            mesh=mesh)

    def forward_packed(self, params, packed_arrays, mesh=None):
        return self._jax_twin.forward_packed(
            self.named_params(params), packed_arrays, mesh=mesh)

    def named_params(self, params) -> functional.Code2VecParams:
        inner = params['params']
        return functional.Code2VecParams(
            token_embedding=inner['token_embedding'],
            path_embedding=inner['path_embedding'],
            target_embedding=inner['target_embedding'],
            transform=inner['transform'],
            attention=inner['attention'])

    def from_canonical(self, named: dict):
        """Canonical {name: array} checkpoint layout → flax module layout."""
        return {'params': dict(named)}


def create_backend(config: Config, vocabs: Code2VecVocabs):
    """Runtime backend selection (reference code2vec.py:7-13)."""
    if config.DL_FRAMEWORK == 'flax':
        return FlaxBackend(config, vocabs)
    if config.DL_FRAMEWORK == 'jax':
        return JaxBackend(config, vocabs)
    raise ValueError('Unknown DL_FRAMEWORK: {!r}'.format(config.DL_FRAMEWORK))
