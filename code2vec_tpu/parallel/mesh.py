"""Device mesh + sharding layout: parallelism as configuration.

The reference is strictly single-device — one ``tf.Session``, no
``tf.distribute``, no collectives anywhere (SURVEY.md §2.3). Here
parallelism is a first-class component, expressed the TPU way: a 2-D
``jax.sharding.Mesh`` with axes

- ``data``  — batch (DP). Gradients are psum-reduced over ICI by XLA because
  params are replicated along this axis.
- ``model`` — parameter sharding (TP). The three embedding tables
  (1.3M/911K/261K rows at full java14m scale, config.py:61-63) are
  row-sharded; the target-embedding sharding also column-shards the final
  softmax logits, so the 261K-way softmax + top-k is computed shard-wise
  with an XLA-inserted collective merge.

Nothing in the model code mentions devices: arrays are *placed* with a
``NamedSharding`` and ``jit`` propagates layouts / inserts collectives
(psum for the DP gradient reduction, all-gather / reduce-scatter around the
sharded gathers and the logits matmul). Multi-host follows the same code
path — ``jax.devices()`` spans hosts and ICI/DCN routing is XLA's job.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from code2vec_tpu.config import Config
from code2vec_tpu.models.functional import Code2VecParams

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def create_mesh(config: Optional[Config] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the (data, model) mesh. ``MESH_DATA_AXIS_SIZE == -1`` means
    'all devices not used by the model axis'.

    ``MESH_DEVICE_INDICES`` (comma-separated indices into
    ``jax.devices()``) restricts the mesh to a device SLICE — how a
    placement-pinned serving-mesh worker builds its sub-mesh over the
    chips its slice owns instead of time-sharing the host's full set
    (SERVING.md "Elastic fleet"). An explicit ``devices`` argument wins
    over the config knob."""
    if devices is None and config is not None and \
            getattr(config, 'MESH_DEVICE_INDICES', ''):
        devices = device_slice(config.MESH_DEVICE_INDICES)
    devices = list(devices if devices is not None else jax.devices())
    model_size = config.MESH_MODEL_AXIS_SIZE if config else 1
    data_size = config.MESH_DATA_AXIS_SIZE if config else -1
    if model_size <= 0:
        model_size = 1
    if data_size <= 0:
        data_size = len(devices) // model_size
    if data_size * model_size != len(devices):
        raise ValueError(
            'Mesh {}x{} does not match {} visible devices.'.format(
                data_size, model_size, len(devices)))
    device_grid = np.asarray(devices).reshape(data_size, model_size)
    return Mesh(device_grid, (DATA_AXIS, MODEL_AXIS))


def device_slice(indices: str) -> list:
    """Resolve a comma-separated index spec ('0,1,2') against
    ``jax.devices()``; raises on malformed, duplicate, or out-of-range
    indices so a misplaced worker fails its handshake typed instead of
    silently building a mesh over the wrong chips."""
    try:
        idx = [int(tok) for tok in indices.split(',') if tok.strip()]
    except ValueError:
        raise ValueError(
            'MESH_DEVICE_INDICES must be comma-separated integers, got '
            '{!r}.'.format(indices))
    if not idx:
        raise ValueError('MESH_DEVICE_INDICES resolved to an empty '
                         'device slice: {!r}.'.format(indices))
    if len(set(idx)) != len(idx):
        raise ValueError('MESH_DEVICE_INDICES has duplicate indices: '
                         '{!r}.'.format(indices))
    all_devices = jax.devices()
    bad = [i for i in idx if i < 0 or i >= len(all_devices)]
    if bad:
        raise ValueError(
            'MESH_DEVICE_INDICES {!r} out of range for {} visible '
            'devices.'.format(bad, len(all_devices)))
    return [all_devices[i] for i in idx]


def partition_device_indices(n_slices: int, per_slice: int) -> list:
    """Partition ``jax.devices()`` index space into ``n_slices``
    DISJOINT contiguous slices of ``per_slice`` devices each — the
    serving mesh's placement table (one slice per replica). Raises when
    the host doesn't have enough devices; contiguity keeps a slice's
    chips ICI-adjacent under the usual host enumeration order."""
    total = len(jax.devices())
    if n_slices * per_slice > total:
        raise ValueError(
            'Placement wants {} slices x {} devices but only {} are '
            'visible (MESH_DEVICES_PER_REPLICA too big for the '
            'replica count).'.format(n_slices, per_slice, total))
    return [list(range(s * per_slice, (s + 1) * per_slice))
            for s in range(n_slices)]


def param_specs() -> Code2VecParams:
    """PartitionSpecs for the five parameter arrays: embedding tables
    row-sharded over ``model``; the small dense/attention params replicated
    (SURVEY.md §2.3 'TPU-native equivalent to build')."""
    return Code2VecParams(
        token_embedding=P(MODEL_AXIS, None),
        path_embedding=P(MODEL_AXIS, None),
        target_embedding=P(MODEL_AXIS, None),
        transform=P(None, None),
        attention=P(None, None),
    )


def batch_spec(ndim: int = 1, shard_contexts: bool = False) -> P:
    """Per-example arrays shard over the batch (data) axis; with
    ``shard_contexts``, 2-D (batch, contexts) arrays additionally shard the
    contexts axis over the model axis — order-free sequence parallelism for
    large bags (the attention reductions compile to XLA collectives).

    Exactly 2-D: the 3-D packed ctx buffer (data/packed.py) is per-shard
    data whose capacity dim must NOT split over the model axis — each
    device holds its own shard's full context stream."""
    if ndim == 2 and shard_contexts:
        return P(DATA_AXIS, MODEL_AXIS)
    return P(DATA_AXIS)


def param_sharding(mesh: Mesh) -> Code2VecParams:
    specs = param_specs()
    return Code2VecParams(*[NamedSharding(mesh, spec) for spec in specs])


def shard_params(params, mesh: Mesh):
    """Place a (possibly host-local) parameter pytree onto the mesh.

    Works for both backends: leaves are matched to their PartitionSpec by
    *name* (the last path component), so the flax ``{'params': {...}}`` dict
    and the raw ``Code2VecParams`` NamedTuple both work regardless of
    flatten order."""
    shardings_by_name = param_sharding(mesh)._asdict()
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed = []
    for path, leaf in path_leaves:
        name = _leaf_name(path)
        if name not in shardings_by_name:
            raise ValueError('Unknown parameter leaf {!r}; expected one of '
                             '{}'.format(name, sorted(shardings_by_name)))
        placed.append(jax.device_put(leaf, shardings_by_name[name]))
    return jax.tree_util.tree_unflatten(treedef, placed)


def _leaf_name(path) -> str:
    last = path[-1]
    return getattr(last, 'key', None) or getattr(last, 'name', str(last))


def sharding_for_tree(tree, mesh: Mesh, zero_partition: bool = False):
    """Shardings for an arbitrary pytree whose leaves either *are* model
    parameters (matched by leaf name, wherever they sit — e.g. inside Adam's
    ``mu``/``nu`` moment trees) or are small scalars/state (replicated).

    This is how optimizer state inherits the parameter layout without any
    per-optimizer code.

    ``zero_partition`` (ZeRO-1-style, ``Config.OPTIMIZER_STATE_SHARDING=
    'zero'``): leaves that would be row-sharded over ``model`` only are
    instead row-sharded over the WHOLE mesh ``(data, model)`` — per-device
    bytes drop by the data-axis size, and XLA turns the consuming update
    into the reduce-scatter/all-gather pair it places itself. Only
    meaningful for the moment trees (params must keep their own layout,
    so never pass it for a parameter pytree)."""
    shardings_by_name = param_sharding(mesh)._asdict()
    if zero_partition:
        zero = NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS), None))
        shardings_by_name = {
            name: zero if s.spec == P(MODEL_AXIS, None) else s
            for name, s in shardings_by_name.items()}
    replicated = NamedSharding(mesh, P())
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = [shardings_by_name.get(_leaf_name(path), replicated)
           for path, _leaf in path_leaves]
    return jax.tree_util.tree_unflatten(treedef, out)


def attach_shardings(abstract_tree, mesh: Mesh, zero_partition: bool = False):
    """ShapeDtypeStruct pytree → same pytree with mesh shardings attached
    (the restore target orbax needs to re-shard onto the *current* mesh)."""
    shardings = sharding_for_tree(abstract_tree, mesh, zero_partition)
    return jax.tree_util.tree_map(
        lambda leaf, s: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                             sharding=s),
        abstract_tree, shardings)


def local_rows(array: jax.Array) -> np.ndarray:
    """Host numpy view of THIS process's rows of a batch-sharded output.

    Multi-host eval pairs device outputs (top-k indices) with host-side
    strings (labels) that only the producing process holds, so each process
    must read back exactly the rows it fed in via
    ``make_array_from_process_local_data``.  Addressable shards are
    deduplicated (model-axis replicas carry identical rows) and stitched in
    ascending global-row order — the order the local batch was provided in.
    """
    if array.is_fully_addressable:
        return np.asarray(array)
    blocks: dict = {}
    for shard in array.addressable_shards:
        index = shard.index
        row0 = (index[0].start or 0) if index else 0
        col0 = (index[1].start or 0) if len(index) > 1 else 0
        cols = blocks.setdefault(row0, {})
        if col0 not in cols:  # skip D2H copies of model-axis replicas
            cols[col0] = np.asarray(shard.data)
    row_blocks = []
    for row0 in sorted(blocks):
        cols = blocks[row0]
        row_blocks.append(
            np.concatenate([cols[c] for c in sorted(cols)], axis=1)
            if len(cols) > 1 else next(iter(cols.values())))
    return np.concatenate(row_blocks, axis=0)


def shard_batch(arrays, mesh: Mesh, shard_contexts: bool = False,
                direct: bool = False):
    """Place a tuple of per-example numpy arrays onto the mesh: batch over
    ``data``; optionally contexts over ``model`` for 2-D arrays.

    ``direct=True`` (the trainer's staging ring) slices each array into
    its per-device shards on the host and issues one batched
    ``device_put`` of the slices straight to their devices, then stitches
    the global array with ``make_array_from_single_device_arrays`` — each
    data-parallel shard crosses the wire exactly once, to its own device,
    instead of relying on the runtime's whole-array placement (which may
    replicate-then-slice through a transfer-bound link). Equal values
    and shardings either way (tests/test_packed.py).

    Multi-host: each process holds its LOCAL 1/process_count share of the
    global batch (the reader strides the data file per process);
    ``make_array_from_process_local_data`` assembles the global sharded
    array without any cross-host copy."""
    if jax.process_count() > 1:
        out = []
        for a in arrays:
            sharding = NamedSharding(mesh,
                                     batch_spec(np.ndim(a), shard_contexts))
            global_shape = ((a.shape[0] * jax.process_count(),)
                            + tuple(a.shape[1:]))
            out.append(jax.make_array_from_process_local_data(
                sharding, np.asarray(a), global_shape))
        return tuple(out)
    if direct and mesh.size > 1:
        from code2vec_tpu.telemetry import core as tele_core
        out = []
        for a in arrays:
            a = np.asarray(a)
            sharding = NamedSharding(mesh,
                                     batch_spec(a.ndim, shard_contexts))
            index_map = sharding.addressable_devices_indices_map(a.shape)
            devices = list(index_map)
            if tele_core.enabled():
                # named scope so per-shard placement slicing shows up
                # against the device lanes in a profiler capture
                with jax.profiler.TraceAnnotation('host/shard_slice'):
                    slices = [np.ascontiguousarray(a[index_map[d]])
                              for d in devices]
            else:
                slices = [np.ascontiguousarray(a[index_map[d]])
                          for d in devices]
            pieces = jax.device_put(slices, devices)
            out.append(jax.make_array_from_single_device_arrays(
                a.shape, sharding, pieces))
        return tuple(out)
    return tuple(
        jax.device_put(a, NamedSharding(
            mesh, batch_spec(np.ndim(a), shard_contexts)))
        for a in arrays)
