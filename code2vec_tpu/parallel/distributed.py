"""Multi-host initialization.

The distributed 'backend' here is not hand-written (the reference had none
at all, and NCCL/MPI-style code would be the wrong shape for TPU): XLA
compiles the collectives, ICI/DCN routing included, once every host joins
one `jax.distributed` runtime and sees the global device set. This module
is the join step.

On Cloud TPU pods the coordinator/process count/process id are
auto-detected; elsewhere they come from the standard env vars
(JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) or explicit
arguments. Single-process runs are a no-op, so the CLI can call this
unconditionally.
"""
from __future__ import annotations

import os
from typing import Optional


def maybe_initialize_distributed(
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        log=None) -> bool:
    """Join the jax.distributed runtime when multi-host config is present.

    Returns True if initialization happened. After it, ``jax.devices()``
    spans all hosts, the mesh spans the pod, each process's reader strides
    the data file (``PathContextReader(process_index, process_count)``) and
    ``parallel.mesh.shard_batch`` assembles the global batch from the
    process-local shards. In-training per-epoch evaluation runs the same
    fixed-step, counter-merged path as standalone ``Code2VecModel.evaluate``
    (exactness across process counts:
    ``tests/test_distributed.py::test_midtrain_eval_matches_single_process``).
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        'JAX_COORDINATOR_ADDRESS')
    env_processes = os.environ.get('JAX_NUM_PROCESSES')
    num_processes = num_processes if num_processes is not None else (
        int(env_processes) if env_processes else None)
    env_pid = os.environ.get('JAX_PROCESS_ID')
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None)

    # a pod is MORE THAN ONE worker: single-host TPU setups (including the
    # axon tunnel, whose sitecustomize sets TPU_WORKER_HOSTNAMES=localhost)
    # must not trigger a coordinator handshake
    worker_hostnames = [h for h in os.environ.get(
        'TPU_WORKER_HOSTNAMES', '').split(',') if h]
    on_tpu_pod = (len(worker_hostnames) > 1
                  or bool(os.environ.get('MEGASCALE_COORDINATOR_ADDRESS')))
    if coordinator_address is None and not on_tpu_pod:
        return False  # single-host run

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
    if log is not None:
        log('jax.distributed initialized: process %d of %d, %d global '
            'devices' % (jax.process_index(), jax.process_count(),
                         len(jax.devices())))
    return True
