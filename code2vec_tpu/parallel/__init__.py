from code2vec_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, batch_sharding, create_mesh, param_sharding,
    param_specs, shard_batch, shard_params)

__all__ = ['DATA_AXIS', 'MODEL_AXIS', 'batch_sharding', 'create_mesh',
           'param_sharding', 'param_specs', 'shard_batch', 'shard_params']
