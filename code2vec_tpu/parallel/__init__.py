from code2vec_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, attach_shardings, batch_spec, create_mesh,
    param_sharding, param_specs, shard_batch, shard_params,
    sharding_for_tree)

__all__ = ['DATA_AXIS', 'MODEL_AXIS', 'attach_shardings', 'batch_spec',
           'create_mesh', 'param_sharding', 'param_specs', 'shard_batch',
           'shard_params', 'sharding_for_tree']
