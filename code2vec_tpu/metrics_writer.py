"""Training-metric summaries (the role of the reference's ``--tensorboard``
flag, config.py:42-43 / keras_model.py:158-163, which attached a Keras
TensorBoard callback).

Scalars are appended as JSON lines to ``<logdir>/metrics.jsonl`` — robust,
dependency-free, and trivially plottable. If TensorBoard's writer is
importable (via torch), an event file is written as well.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional


class MetricsWriter:
    def __init__(self, logdir: str):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = open(os.path.join(logdir, 'metrics.jsonl'), 'a')
        self._tb = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore
            self._tb = SummaryWriter(log_dir=logdir)
        except Exception:
            self._tb = None

    def scalar(self, tag: str, value: float, step: int) -> None:
        record = {'tag': tag, 'value': float(value), 'step': int(step),
                  'time': time.time()}
        self._jsonl.write(json.dumps(record) + '\n')
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)

    def close(self) -> None:
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


def maybe_create(config) -> Optional[MetricsWriter]:
    """A writer when ``--tensorboard`` was passed and a place to write
    exists (next to the model, like the reference's log dir)."""
    if not config.USE_TENSORBOARD:
        return None
    if config.is_saving:
        logdir = os.path.join(os.path.dirname(config.MODEL_SAVE_PATH),
                              'summaries')
    elif config.is_loading:
        logdir = os.path.join(config.model_load_dir, 'summaries')
    else:
        logdir = 'summaries'
    return MetricsWriter(logdir)
