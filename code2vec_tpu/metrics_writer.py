"""Training-metric summaries (the role of the reference's ``--tensorboard``
flag, config.py:42-43 / keras_model.py:158-163, which attached a Keras
TensorBoard callback).

Scalars are appended as JSON lines to ``<logdir>/metrics.jsonl`` — robust,
dependency-free, and trivially plottable (the telemetry exporters write the
same record schema).  If TensorBoard's writer is importable (via torch), an
event file is written as well.

Lifecycle: writes are BUFFERED (one file append per ``BUFFER_RECORDS``
scalars, not per scalar) and the file handle only exists inside each
flush, so nothing leaks if ``close()`` is never reached; an ``atexit``
hook flushes whatever a crashing/forgetful caller left buffered.  Usable
as a context manager.
"""
from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from typing import List, Optional

# package logger: 'code2vec_tpu.metrics_writer' — propagates to the
# 'code2vec_tpu' root logger Config.get_logger configures
logger = logging.getLogger(__name__)

# One disk append per this many scalars. fit() emits 2 scalars per log
# window (train/loss + examples_per_sec), so 8 keeps a plotting tail -f
# within ~4 log windows — while still batching I/O 8x vs the old
# flush-per-scalar (eval scalars are flushed explicitly, model_api).
BUFFER_RECORDS = 8


class MetricsWriter:
    def __init__(self, logdir: str, buffer_records: int = BUFFER_RECORDS):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, 'metrics.jsonl')
        # the trainer thread and the atexit/close path both flush
        # (lock-discipline rule, ANALYSIS.md):
        # graftlint: guard MetricsWriter._buffer by _lock
        self._buffer: List[str] = []
        self._buffer_records = max(1, buffer_records)
        self._lock = threading.Lock()
        self._closed = False
        # dropped-write accounting (ISSUE 3 satellite): a read-only or
        # full disk must neither crash training nor masquerade as a
        # healthy run — the FIRST failure is logged, later ones counted
        self._write_failures = 0
        self._dropped_records = 0
        # a crashed or non-closing run still gets its buffered tail
        atexit.register(self._atexit_flush)
        self._tb = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore
            self._tb = SummaryWriter(log_dir=logdir)
        except Exception:
            self._tb = None

    def scalar(self, tag: str, value: float, step: int) -> None:
        record = {'tag': tag, 'value': float(value), 'step': int(step),
                  'time': time.time()}
        with self._lock:
            self._buffer.append(json.dumps(record))
            if len(self._buffer) >= self._buffer_records:
                self._flush_locked()
        if self._tb is not None:
            try:
                self._tb.add_scalar(tag, value, step)
            except Exception as exc:
                # the event-file mirror is best-effort, but its death
                # must be visible once, not swallowed forever
                logger.warning('metrics writer: tensorboard mirror failed '
                               '(%s); disabling it for this writer', exc)
                self._tb = None

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()
        if self._tb is not None:
            self._tb.flush()

    def _flush_locked(self) -> None:
        if not self._buffer:
            return
        try:
            # open-per-flush append: no long-lived handle to leak between
            # flushes, and append mode keeps resumed runs' streams intact
            with open(self._path, 'a') as f:
                f.write('\n'.join(self._buffer) + '\n')
        except OSError as exc:
            # metric persistence must never take down the training run —
            # but it must not fail SILENTLY either: log the first failure
            # (rate-limited to once per writer; close() reports the total)
            self._write_failures += 1
            self._dropped_records += len(self._buffer)
            if self._write_failures == 1:
                logger.warning(
                    'metrics writer: appending to `%s` failed (%s) — '
                    'metric records will be DROPPED until writes recover; '
                    'further failures are logged once at close', self._path,
                    exc)
        self._buffer = []

    def _atexit_flush(self) -> None:
        try:
            if not self._closed:
                self.flush()
        except Exception:
            pass  # interpreter teardown: never mask the real exit

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        if self._dropped_records:
            logger.warning(
                'metrics writer: %d record(s) dropped across %d failed '
                'append(s) to `%s` (read-only or full disk?)',
                self._dropped_records, self._write_failures, self._path)
        self._closed = True
        atexit.unregister(self._atexit_flush)
        if self._tb is not None:
            self._tb.close()

    def __enter__(self) -> 'MetricsWriter':
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def maybe_create(config) -> Optional[MetricsWriter]:
    """A writer when ``--tensorboard`` was passed and a place to write
    exists (next to the model, like the reference's log dir)."""
    if not config.USE_TENSORBOARD:
        return None
    if config.is_saving:
        logdir = os.path.join(os.path.dirname(config.MODEL_SAVE_PATH),
                              'summaries')
    elif config.is_loading:
        logdir = os.path.join(config.model_load_dir, 'summaries')
    else:
        logdir = 'summaries'
    return MetricsWriter(logdir)
