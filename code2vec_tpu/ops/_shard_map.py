"""One import of ``shard_map`` that works across jax versions.

jax promoted shard_map out of ``jax.experimental`` around 0.5 (first as
a ``jax.shard_map`` module attribute, then as a top-level function) and
renamed its replication-check kwarg ``check_rep`` -> ``check_vma``; the
toolchain baked into this image carries 0.4.x where only the
experimental path and the old kwarg exist. Every in-repo user imports
from here (spelling the NEW kwarg name) so the version dance has a
single definition.
"""
import inspect

try:
    from jax import shard_map as _impl  # jax >= 0.5
    # module in some versions, function in others
    _impl = getattr(_impl, 'shard_map', _impl)
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _impl

_KWARGS = inspect.signature(_impl).parameters


def shard_map(f, **kwargs):
    if 'check_vma' in kwargs and 'check_vma' not in _KWARGS:
        kwargs['check_rep'] = kwargs.pop('check_vma')
    return _impl(f, **kwargs)
