"""Lazy (sparse-row) Adam for the giant embedding tables.

Why: at java14m scale the token/path tables hold 283M of the model's 384M
parameters (reference config.py:61-64), but one batch touches at most
B*C*2 + B*C = 614,400 rows — under 28% of the rows, with heavy repetition.
A dense Adam update walks params+mu+nu for EVERY row every step (~8 GB of
HBM traffic); updating only the touched rows makes the optimizer cost
proportional to the batch, not the vocabulary.

MEASURED VERDICT (2026-07-29, v5e-class chip, PERF.md): 90.85 ms/step vs
49.25 dense — the gathered-row scatter update breaks XLA's streaming
dense-Adam fusion and loses 1.85×. Kept as a tested opt-in (the
trade-off may flip on meshes where the tables are row-sharded and the
dense walk crosses chips), but the default stays dense on evidence.

Semantics: `tf.contrib.opt.LazyAdamOptimizer` — moments decay and rows
move only when present in the batch, with bias correction from the GLOBAL
step:

    lr_t = lr * sqrt(1 - b2^t) / (1 - b1^t)
    m    = b1 * m + (1 - b1) * g          (touched rows only)
    v    = b2 * v + (1 - b2) * g^2        (touched rows only)
    p    = p - lr_t * m / (sqrt(v) + eps)

NOTE this is deliberately NOT the reference's exact optimizer: the
reference's `tf.compat.v1.train.AdamOptimizer` decays m/v DENSELY over the
whole table and applies a dense var update even for IndexedSlices
gradients (`_apply_sparse_shared`: `m.assign(m * beta1)` then scatter-add)
— which is what the default dense optax Adam reproduces. The lazy variant
is the standard throughput trade-off for giant embedding tables (rows
without gradient keep stale moments and skip their momentum drift); it is
opt-in (`LAZY_EMBEDDING_ADAM`) and off by default.

Duplicate rows: ``dense_grad`` is the scatter-added gradient array, so
every duplicate of a row reads the SAME aggregated gradient and computes
the SAME updated row — the scatter writes are idempotent and the result is
deterministic regardless of duplicate count or order. This also makes the
formulation pjit-safe: with the batch sharded over the data axis and the
table row-sharded over the model axis, XLA routes the row updates to the
owning shards.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax


def sparse_row_adam(table: jax.Array, mu: jax.Array, nu: jax.Array,
                    dense_grad: jax.Array, rows: jax.Array, *,
                    learning_rate: float, step: jax.Array,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One lazy-Adam update of ``table`` at ``rows`` (may repeat).

    ``step`` is the 1-based global step (int scalar) for bias correction;
    ``dense_grad`` is the full-shape gradient array (only its touched rows
    are read). Returns (new_table, new_mu, new_nu); untouched rows of all
    three are bit-identical to the inputs.
    """
    rows = rows.reshape(-1)
    g = dense_grad[rows]                               # (N, d)
    m = b1 * mu[rows] + (1.0 - b1) * g
    v = b2 * nu[rows] + (1.0 - b2) * (g * g)
    t = step.astype(jnp.float32)
    lr_t = learning_rate * jnp.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)
    new_rows = table[rows] - lr_t * m / (jnp.sqrt(v) + eps)
    return (table.at[rows].set(new_rows),
            mu.at[rows].set(m),
            nu.at[rows].set(v))


class LazyAdamState(NamedTuple):
    """Optimizer state for LazyEmbeddingAdam. ``mu``/``nu`` are dicts keyed
    by the table's canonical parameter name so the mesh layout machinery
    (mesh.sharding_for_tree matches leaves by name) row-shards the moments
    exactly like the tables they mirror."""
    dense: Any   # optax state over {'target_embedding','transform','attention'}
    mu: dict     # {'token_embedding': ..., 'path_embedding': ...}
    nu: dict


class LazyEmbeddingAdam:
    """Adam with TF1 sparse-row updates for the token/path tables and
    ordinary optax Adam for everything dense (see module docstring).

    Backend-agnostic: parameter trees are viewed through the backend's
    canonical named layout (``named_params`` / ``from_canonical``), so the
    raw-pytree jax backend and the flax backend share this code.
    """

    DENSE_KEYS = ('target_embedding', 'transform', 'attention')
    SPARSE_KEYS = ('token_embedding', 'path_embedding')

    def __init__(self, learning_rate: float, backend,
                 b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
        self.learning_rate = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.backend = backend
        self._dense = optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)

    def init(self, params) -> LazyAdamState:
        named = self.backend.named_params(params)
        dense = {k: getattr(named, k) for k in self.DENSE_KEYS}
        zeros = {k: jnp.zeros_like(getattr(named, k))
                 for k in self.SPARSE_KEYS}
        return LazyAdamState(
            dense=self._dense.init(dense),
            mu=zeros,
            nu={k: jnp.zeros_like(v) for k, v in zeros.items()})

    def update_sparse(self, params, grads, opt_state: LazyAdamState,
                      step: jax.Array, source: jax.Array, path: jax.Array,
                      target: jax.Array):
        """One optimizer step. ``step`` is the completed-steps counter
        (0-based); bias correction uses step+1. ``source``/``path``/
        ``target`` are the batch index arrays that define the touched rows.
        Returns (new_params, new_opt_state)."""
        named_p = self.backend.named_params(params)
        named_g = self.backend.named_params(grads)
        dense_p = {k: getattr(named_p, k) for k in self.DENSE_KEYS}
        dense_g = {k: getattr(named_g, k) for k in self.DENSE_KEYS}
        updates, new_dense = self._dense.update(dense_g, opt_state.dense,
                                                dense_p)
        dense_new = optax.apply_updates(dense_p, updates)

        t = step + 1
        token_rows = jnp.concatenate([source.reshape(-1),
                                      target.reshape(-1)])
        new_tok, m_tok, v_tok = sparse_row_adam(
            named_p.token_embedding, opt_state.mu['token_embedding'],
            opt_state.nu['token_embedding'], named_g.token_embedding,
            token_rows, learning_rate=self.learning_rate, step=t,
            b1=self.b1, b2=self.b2, eps=self.eps)
        new_path, m_path, v_path = sparse_row_adam(
            named_p.path_embedding, opt_state.mu['path_embedding'],
            opt_state.nu['path_embedding'], named_g.path_embedding,
            path.reshape(-1), learning_rate=self.learning_rate, step=t,
            b1=self.b1, b2=self.b2, eps=self.eps)

        new_named = dict(dense_new, token_embedding=new_tok,
                         path_embedding=new_path)
        new_params = self.backend.from_canonical(new_named)
        new_opt = LazyAdamState(
            dense=new_dense,
            mu={'token_embedding': m_tok, 'path_embedding': m_path},
            nu={'token_embedding': v_tok, 'path_embedding': v_path})
        return new_params, new_opt
