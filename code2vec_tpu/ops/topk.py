"""Cross-shard top-k merge for the column-sharded target softmax.

The reference's top-k runs on a single device over the full 261K-way score
matrix (tensorflow_model.py:299-302). With the target table column-sharded
over the ``model`` mesh axis, the naive jit lowering all-gathers the full
logits (B × V floats over ICI) before a replicated top-k. This shard_map
kernel does the standard two-stage merge instead:

  1. each shard computes a LOCAL top-k over its V/m logit columns;
  2. only the k candidates per shard (values + globalized indices) are
     all-gathered — k·m ≪ V/m traffic (k=10, m=8, V=261K: ~80 floats vs
     ~32K per example);
  3. a final top-k over the m·k candidates yields the exact global result
     (ties broken by shard order rather than pure index order — the only
     deviation from the single-device semantics).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from code2vec_tpu.ops._shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from code2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def grouped_top_k(x: jax.Array, k: int, group_size: int = 2048
                  ) -> Tuple[jax.Array, jax.Array]:
    """EXACT top-k over the last axis via a two-stage group merge.

    Stage 1 takes top-k within each ``group_size`` slice of the vocab
    axis; stage 2 takes top-k over the groups*k candidates. Every global
    top-k element is necessarily in its group's top-k, so the result is
    exact — and tie-breaking matches ``lax.top_k`` (lowest index wins):
    within a group by lax.top_k itself, across groups because candidates
    are ordered by group and groups cover ascending index ranges.

    Motivation: one monolithic top-k over a (B, 261K) logits matrix makes
    the selection network as wide as the vocab; two narrow stages map
    better onto the VPU. Whether that wins on a given chip is measured,
    not assumed (benchmarks/diag_step_breakdown.py stages a lax-vs-grouped
    A/B); callers opt in explicitly.

    MEASURED VERDICT (2026-07-29, v5e-class chip, PERF.md): 119.3 ms vs
    lax.top_k's 24.8 ms at (1024, 261K), k=10 — XLA's monolithic top-k
    wins 4.8×; nothing routes here in production. Retained as a tested,
    documented negative result.
    """
    v = x.shape[-1]
    if v <= group_size or k >= group_size:
        return jax.lax.top_k(x, k)
    lead = x.shape[:-1]
    groups = -(-v // group_size)
    pad = groups * group_size - v
    if pad:
        pad_widths = [(0, 0)] * len(lead) + [(0, pad)]
        x = jnp.pad(x, pad_widths, constant_values=-jnp.inf)
    grouped = x.reshape(*lead, groups, group_size)
    group_values, group_indices = jax.lax.top_k(grouped, k)  # (..., G, k)
    base = (jnp.arange(groups, dtype=group_indices.dtype)
            * group_size)[:, None]
    cand_values = group_values.reshape(*lead, groups * k)
    cand_indices = (group_indices + base).reshape(*lead, groups * k)
    final_values, positions = jax.lax.top_k(cand_values, k)
    final_indices = jnp.take_along_axis(cand_indices, positions, axis=-1)
    return final_values, final_indices


def sharded_top_k(logits: jax.Array, k: int, mesh: Mesh
                  ) -> Tuple[jax.Array, jax.Array]:
    """Top-k over the last (vocab) axis of ``logits`` laid out
    ``P(data, model)`` on ``mesh``. Returns (values, indices), both
    ``P(data, None)``.

    Falls back to ``lax.top_k`` when the model axis is trivial.
    ``k`` may exceed the per-shard width V/m (as long as k <= V): each
    shard then contributes all of its columns as candidates.
    """
    model_size = mesh.shape[MODEL_AXIS]
    k = min(k, logits.shape[-1])
    if model_size == 1:
        return jax.lax.top_k(logits, k)

    def local_merge(local_logits):
        # local_logits: (B/d, V/m) on each (data, model) shard
        local_k = min(k, local_logits.shape[-1])
        local_values, local_indices = jax.lax.top_k(local_logits, local_k)
        shard = jax.lax.axis_index(MODEL_AXIS)
        global_indices = local_indices + shard * local_logits.shape[-1]
        # gather local_k candidates per shard along the model axis
        all_values = jax.lax.all_gather(local_values, MODEL_AXIS)
        all_indices = jax.lax.all_gather(global_indices, MODEL_AXIS)
        # (m, B/d, local_k) -> (B/d, m*local_k); m*local_k >= k always
        all_values = jnp.moveaxis(all_values, 0, 1).reshape(
            local_values.shape[0], -1)
        all_indices = jnp.moveaxis(all_indices, 0, 1).reshape(
            local_values.shape[0], -1)
        merged_values, positions = jax.lax.top_k(all_values, k)
        merged_indices = jnp.take_along_axis(all_indices, positions, axis=1)
        return merged_values, merged_indices

    # check_vma=False: outputs ARE replicated along 'model' (post
    # all_gather + identical merge on every shard) but the static checker
    # can't prove it
    return shard_map(local_merge, mesh=mesh,
                     in_specs=(P(DATA_AXIS, MODEL_AXIS),),
                     out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
                     check_vma=False)(logits)
