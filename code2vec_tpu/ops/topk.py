"""Cross-shard top-k merge for the column-sharded target softmax.

The reference's top-k runs on a single device over the full 261K-way score
matrix (tensorflow_model.py:299-302). With the target table column-sharded
over the ``model`` mesh axis, the naive jit lowering all-gathers the full
logits (B × V floats over ICI) before a replicated top-k. This shard_map
kernel does the standard two-stage merge instead:

  1. each shard computes a LOCAL top-k over its V/m logit columns;
  2. only the k candidates per shard (values + globalized indices) are
     all-gathered — k·m ≪ V/m traffic (k=10, m=8, V=261K: ~80 floats vs
     ~32K per example);
  3. a final top-k over the m·k candidates yields the exact global result.
     Tie-breaking is by LOWEST GLOBAL INDEX, matching single-device
     ``lax.top_k``: shards own ascending index ranges, each shard's
     candidates are emitted in (value desc, index asc) order, and the
     merge's ``lax.top_k`` picks the leftmost of equal values — which is
     always the lowest global index (tested in tests/test_topk_merge.py).

The same merge shape serves the embedding index (code2vec_tpu/index/):
``sharded_top_k`` is axis-general (the index's store shards over the
*data* axis where the softmax shards over *model*), and the
``padded_local_topk`` / ``merge_topk_host`` pair implements the
host-side streamed merge across store shards, where a shard may hold
FEWER than k rows (k > n_shard pads with −inf/−1 sentinels that the
merge drops).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from code2vec_tpu.ops._shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from code2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# Index sentinel for padded top-k slots (k > n): value is -inf, index is
# -1 — never a valid row, and np.take-safe (wraps to the last row, whose
# score the merge has already discarded).
PAD_INDEX = -1


def grouped_top_k(x: jax.Array, k: int, group_size: int = 2048
                  ) -> Tuple[jax.Array, jax.Array]:
    """EXACT top-k over the last axis via a two-stage group merge.

    Stage 1 takes top-k within each ``group_size`` slice of the vocab
    axis; stage 2 takes top-k over the groups*k candidates. Every global
    top-k element is necessarily in its group's top-k, so the result is
    exact — and tie-breaking matches ``lax.top_k`` (lowest index wins):
    within a group by lax.top_k itself, across groups because candidates
    are ordered by group and groups cover ascending index ranges.

    Motivation: one monolithic top-k over a (B, 261K) logits matrix makes
    the selection network as wide as the vocab; two narrow stages map
    better onto the VPU. Whether that wins on a given chip is measured,
    not assumed (benchmarks/diag_step_breakdown.py stages a lax-vs-grouped
    A/B); callers opt in explicitly.

    MEASURED VERDICT (2026-07-29, v5e-class chip, PERF.md): 119.3 ms vs
    lax.top_k's 24.8 ms at (1024, 261K), k=10 — XLA's monolithic top-k
    wins 4.8×; nothing routes here in production. Retained as a tested,
    documented negative result.
    """
    v = x.shape[-1]
    # cap like sharded_top_k: lax.top_k rejects k > axis length
    k = min(k, v)
    if v <= group_size or k >= group_size:
        return jax.lax.top_k(x, k)
    lead = x.shape[:-1]
    groups = -(-v // group_size)
    pad = groups * group_size - v
    if pad:
        pad_widths = [(0, 0)] * len(lead) + [(0, pad)]
        x = jnp.pad(x, pad_widths, constant_values=-jnp.inf)
    grouped = x.reshape(*lead, groups, group_size)
    group_values, group_indices = jax.lax.top_k(grouped, k)  # (..., G, k)
    base = (jnp.arange(groups, dtype=group_indices.dtype)
            * group_size)[:, None]
    cand_values = group_values.reshape(*lead, groups * k)
    cand_indices = (group_indices + base).reshape(*lead, groups * k)
    final_values, positions = jax.lax.top_k(cand_values, k)
    final_indices = jnp.take_along_axis(cand_indices, positions, axis=-1)
    return final_values, final_indices


def sharded_top_k(logits: jax.Array, k: int, mesh: Mesh,
                  shard_axis: str = MODEL_AXIS,
                  batch_axis: str = DATA_AXIS
                  ) -> Tuple[jax.Array, jax.Array]:
    """Top-k over the last axis of ``logits`` laid out
    ``P(batch_axis, shard_axis)`` on ``mesh``. Returns (values, indices),
    both ``P(batch_axis, None)``.

    The default axes are the softmax layout (batch over ``data``, vocab
    columns over ``model``); the embedding index calls it with
    ``shard_axis=DATA_AXIS, batch_axis=None`` — queries replicated, store
    rows (the score columns) sharded over the data axis
    (code2vec_tpu/index/exact.py).

    Falls back to ``lax.top_k`` when the shard axis is trivial.
    ``k`` may exceed the per-shard width V/m (as long as k <= V): each
    shard then contributes all of its columns as candidates.
    """
    shard_size = mesh.shape[shard_axis]
    k = min(k, logits.shape[-1])
    if shard_size == 1:
        return jax.lax.top_k(logits, k)

    def local_merge(local_logits):
        # local_logits: (B/d, V/m) on each (batch, shard) shard
        local_k = min(k, local_logits.shape[-1])
        local_values, local_indices = jax.lax.top_k(local_logits, local_k)
        shard = jax.lax.axis_index(shard_axis)
        global_indices = local_indices + shard * local_logits.shape[-1]
        # gather local_k candidates per shard along the shard axis
        all_values = jax.lax.all_gather(local_values, shard_axis)
        all_indices = jax.lax.all_gather(global_indices, shard_axis)
        # (m, B/d, local_k) -> (B/d, m*local_k); m*local_k >= k always
        all_values = jnp.moveaxis(all_values, 0, 1).reshape(
            local_values.shape[0], -1)
        all_indices = jnp.moveaxis(all_indices, 0, 1).reshape(
            local_values.shape[0], -1)
        merged_values, positions = jax.lax.top_k(all_values, k)
        merged_indices = jnp.take_along_axis(all_indices, positions, axis=1)
        return merged_values, merged_indices

    # check_vma=False: outputs ARE replicated along the shard axis (post
    # all_gather + identical merge on every shard) but the static checker
    # can't prove it
    return shard_map(local_merge, mesh=mesh,
                     in_specs=(P(batch_axis, shard_axis),),
                     out_specs=(P(batch_axis), P(batch_axis)),
                     check_vma=False)(logits)


def padded_local_topk(x: jax.Array, k: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """``lax.top_k`` over the last axis where ``k`` MAY exceed the axis
    length: the result is padded to exactly ``k`` slots with ``-inf``
    values and ``PAD_INDEX`` indices, so per-shard candidate lists from
    unevenly-sized shards stack rectangularly and ``merge_topk_host``
    can drop the sentinels. Traceable (static shapes only)."""
    n = x.shape[-1]
    local_k = min(k, n)
    values, indices = jax.lax.top_k(x, local_k)
    if local_k < k:
        pad_widths = [(0, 0)] * (x.ndim - 1) + [(0, k - local_k)]
        values = jnp.pad(values, pad_widths, constant_values=-jnp.inf)
        indices = jnp.pad(indices, pad_widths,
                          constant_values=PAD_INDEX)
    return values, indices


def merge_topk_host(values: np.ndarray, indices: np.ndarray, k: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side EXACT merge of per-shard top-k candidates.

    ``values``/``indices`` are ``(..., m)`` numpy arrays of candidate
    scores and GLOBAL row indices — typically the concatenation of each
    shard's ``padded_local_topk`` output with per-shard offsets already
    applied. Sentinel slots (``-inf`` value / ``PAD_INDEX``) sort past
    every real candidate and are returned only when fewer than ``k``
    real candidates exist in total.

    Deterministic: ties break by LOWEST index (``np.lexsort`` with the
    index as the secondary key), matching ``lax.top_k`` single-device
    semantics — property-tested against ``np.argsort`` in
    tests/test_topk_merge.py."""
    values = np.asarray(values)
    indices = np.asarray(indices)
    if values.shape != indices.shape:
        raise ValueError('values %r and indices %r must agree in shape'
                         % (values.shape, indices.shape))
    k = min(k, values.shape[-1])
    # primary key: value DESC; secondary: index ASC (lexsort's last key
    # is primary). -(-inf) = +inf sorts sentinels last.
    order = np.lexsort((indices, -values), axis=-1)[..., :k]
    return (np.take_along_axis(values, order, axis=-1),
            np.take_along_axis(indices, order, axis=-1))
