"""Cross-shard top-k merge for the column-sharded target softmax.

The reference's top-k runs on a single device over the full 261K-way score
matrix (tensorflow_model.py:299-302). With the target table column-sharded
over the ``model`` mesh axis, the naive jit lowering all-gathers the full
logits (B × V floats over ICI) before a replicated top-k. This shard_map
kernel does the standard two-stage merge instead:

  1. each shard computes a LOCAL top-k over its V/m logit columns;
  2. only the k candidates per shard (values + globalized indices) are
     all-gathered — k·m ≪ V/m traffic (k=10, m=8, V=261K: ~80 floats vs
     ~32K per example);
  3. a final top-k over the m·k candidates yields the exact global result
     (ties broken by shard order rather than pure index order — the only
     deviation from the single-device semantics).
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from code2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def sharded_top_k(logits: jax.Array, k: int, mesh: Mesh
                  ) -> Tuple[jax.Array, jax.Array]:
    """Top-k over the last (vocab) axis of ``logits`` laid out
    ``P(data, model)`` on ``mesh``. Returns (values, indices), both
    ``P(data, None)``.

    Falls back to ``lax.top_k`` when the model axis is trivial.
    ``k`` may exceed the per-shard width V/m (as long as k <= V): each
    shard then contributes all of its columns as candidates.
    """
    model_size = mesh.shape[MODEL_AXIS]
    k = min(k, logits.shape[-1])
    if model_size == 1:
        return jax.lax.top_k(logits, k)

    def local_merge(local_logits):
        # local_logits: (B/d, V/m) on each (data, model) shard
        local_k = min(k, local_logits.shape[-1])
        local_values, local_indices = jax.lax.top_k(local_logits, local_k)
        shard = jax.lax.axis_index(MODEL_AXIS)
        global_indices = local_indices + shard * local_logits.shape[-1]
        # gather local_k candidates per shard along the model axis
        all_values = jax.lax.all_gather(local_values, MODEL_AXIS)
        all_indices = jax.lax.all_gather(global_indices, MODEL_AXIS)
        # (m, B/d, local_k) -> (B/d, m*local_k); m*local_k >= k always
        all_values = jnp.moveaxis(all_values, 0, 1).reshape(
            local_values.shape[0], -1)
        all_indices = jnp.moveaxis(all_indices, 0, 1).reshape(
            local_values.shape[0], -1)
        merged_values, positions = jax.lax.top_k(all_values, k)
        merged_indices = jnp.take_along_axis(all_indices, positions, axis=1)
        return merged_values, merged_indices

    # check_vma=False: outputs ARE replicated along 'model' (post
    # all_gather + identical merge on every shard) but the static checker
    # can't prove it
    return shard_map(local_merge, mesh=mesh,
                     in_specs=(P(DATA_AXIS, MODEL_AXIS),),
                     out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
                     check_vma=False)(logits)
