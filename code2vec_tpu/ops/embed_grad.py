"""Embedding-table gradient strategies for the giant token/path tables.

The train step's backward pass turns each table gather (``jnp.take`` in
models/functional.py::encode) into a scatter-add of B*C = 204,800 rows into
a 1.3M/911K-row table (reference forward: tensorflow_model.py:236-244; the
reference left this entirely to TF's ``IndexedSlices`` machinery on GPU).
On TPU, XLA lowers a scatter-add with *possibly-duplicate* indices
conservatively — duplicate hits on a row must be ordered — which is the
leading suspect for the measured gap between the 49.25 ms java14m step and
its ~25 ms HBM roofline (PERF.md; isolated by the frozen-tables variant in
benchmarks/diag_step_breakdown.py).

This module provides ``take_rows``, a drop-in gather whose *backward* is
selectable:

- ``'dense'``  — plain autodiff scatter-add (the default; XLA decides).
- ``'sorted'`` — sort the flattened indices once, permute the incoming
  cotangent rows to match, and scatter with ``indices_are_sorted=True``:
  duplicate hits on a row become adjacent, which XLA can turn into local
  accumulation instead of remote row revisits.
- ``'dedup'``  — as ``'sorted'``, then pre-combine duplicate rows with a
  segmented associative scan so each table row is written by AT MOST one
  update; non-final duplicates are redirected to an out-of-range sentinel
  and dropped. The scatter that reaches HBM touches each row once, at the
  price of one log-depth scan over the (N, d) cotangent block.

All three are numerically equivalent up to fp summation order (tested
exactly at fp32 against autodiff in tests/test_embed_grad.py). The knob is
``Config.EMBED_GRAD_IMPL``; the default stays ``'dense'`` until the
on-chip A/B (benchmarks/bench_embed_grad.py) records a win.

Duplicate-row statistics decide how much ``'dedup'`` can save: uniform
synthetic indices (benchlib.random_batches) hit ~93% unique rows, while
real corpora are Zipfian — java14m token draws repeat heavily, so the
A/B measures both distributions.

Mesh caveat: the backward sorts the FLATTENED (B*C) index stream. With
the batch sharded over the data axis, a global sort makes XLA's
partitioner insert cross-shard exchanges; correctness on a (4, 2) mesh is
tested (tests/test_embed_grad.py), but the A/B verdict is a SINGLE-CHIP
number — on multi-chip meshes the scatter-add is per-shard already
(followed by the grad psum), so re-measure before assuming the verdict
transfers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

IMPLS = ('dense', 'sorted', 'dedup')


def take_rows(table: jax.Array, idx: jax.Array, *,
              impl: str = 'dense') -> jax.Array:
    """``jnp.take(table, idx, axis=0)`` with a selectable gradient path.

    ``impl='dense'`` is literally ``jnp.take`` (no custom_vjp wrapping, so
    autodiff, vjp-of-vjp, and jvp all behave exactly as before). The other
    impls close over the table's static shape/dtype, so the custom_vjp is
    built per call site — traced once per jit like everything else.
    """
    if impl == 'dense':
        return jnp.take(table, idx, axis=0)
    if impl not in IMPLS:
        raise ValueError('embed grad impl must be one of %s, got %r'
                         % (IMPLS, impl))
    num_rows, table_dtype = table.shape[0], table.dtype

    @jax.custom_vjp
    def gather(t, i):
        return jnp.take(t, i, axis=0)

    def gather_fwd(t, i):
        return jnp.take(t, i, axis=0), i

    def gather_bwd(i, g):
        return table_grad(g, i, num_rows, table_dtype, impl), None

    gather.defvjp(gather_fwd, gather_bwd)
    return gather(table, idx)


def _segmented_sum_combine(a, b):
    """Associative operator for a segmented inclusive prefix sum: values
    accumulate left-to-right but reset wherever the right operand starts a
    new segment."""
    value_a, start_a = a
    value_b, start_b = b
    value = jnp.where(start_b[..., None], value_b, value_a + value_b)
    return value, start_a | start_b


def table_grad(g: jax.Array, idx: jax.Array, num_rows: int,
               table_dtype, impl: str) -> jax.Array:
    """Accumulate cotangent rows ``g`` (..., d) at ``idx`` (...) into a
    dense (num_rows, d) table gradient using the chosen strategy."""
    d = g.shape[-1]
    flat_g = g.reshape(-1, d).astype(table_dtype)
    flat_idx = idx.reshape(-1)
    if impl == 'dense':
        return jnp.zeros((num_rows, d), table_dtype).at[flat_idx].add(flat_g)

    order = jnp.argsort(flat_idx)
    sorted_idx = jnp.take(flat_idx, order)
    sorted_g = jnp.take(flat_g, order, axis=0)
    if impl == 'sorted':
        return jnp.zeros((num_rows, d), table_dtype).at[sorted_idx].add(
            sorted_g, indices_are_sorted=True)

    assert impl == 'dedup'
    # run starts: first row of each group of equal indices
    starts = jnp.concatenate([
        jnp.ones((1,), bool), sorted_idx[1:] != sorted_idx[:-1]])
    # inclusive segmented prefix sum: at each run's LAST row this holds the
    # full per-row gradient sum
    summed, _ = jax.lax.associative_scan(
        _segmented_sum_combine, (sorted_g, starts))
    is_end = jnp.concatenate([starts[1:], jnp.ones((1,), bool)])
    # redirect non-final duplicates out of range; mode='drop' discards
    # them, so each surviving update hits a distinct row. NO
    # indices_are_sorted hint: the sentinel lands BEFORE each run's final
    # element, so the rewritten stream is not sorted — claiming it is
    # would be undefined behavior on TPU.
    scatter_idx = jnp.where(is_end, sorted_idx, num_rows)
    return jnp.zeros((num_rows, d), table_dtype).at[scatter_idx].add(
        summed, mode='drop')
