"""Ragged fused encode + attention straight off the packed wire.

The packed wire format (data/packed.py) ships each batch as per-shard
dense ``(data_shards, capacity, 3)`` context triples plus per-example
``count``s. Until now every consumer paid a full ``(B, max_contexts)``
segment-scatter (``unpack_device``) back to plane layout BEFORE the
encoder ran — at the java14m fill rate (contexts/method p50 28 of 200)
that materializes ~4x more context slots than the batch actually holds,
and the dense encode then spends FLOPs and HBM traffic on every one of
them. This module walks the packed segments directly instead:

  per slot t of the packed stream (slots past a shard's total and
  interior all-PAD holes are masked OUT, matching the dense path's
  ``log(1e-30)`` masking to fp32 rounding):

    e_t = [tok[src_t] ; path[pth_t] ; tok[tgt_t]]            gather
    x_t = tanh(e_t @ TRANSFORM)        (row-split, no concat) encode
    s_t = x_t . ATTENTION                                     score

  per example i (a SEGMENT of the stream, delimited by ``count``):

    m_i  = max_t s_t                 \\  single-pass max-sum softmax
    z_i  = sum_t exp(s_t - m_i)       |  (FuseMax, arxiv 2406.10491):
    c_i  = sum_t exp(s_t - m_i) x_t  /   one walk, no separate sweeps
    code_i = c_i / z_i

Two interchangeable implementations produce the same ``(scores, m, z,
acc)`` statistics:

- ``_stats_jnp`` — the reference twin: plain jnp segment ops (scatter
  max/add over the shard-structured stream), fully differentiable, runs
  everywhere and partitions under GSPMD (leading data_shards axis, like
  ``unpack_device``). This is the TRAIN path: dropout and the backward
  pass live here, and skipping the dense scatter + dense encode is
  already the structural win.
- ``_stats_pallas`` — the Pallas TPU kernel: one grid walk over slot
  tiles with the per-example running ``(m, z, acc)`` resident in VMEM,
  segment membership resolved per tile with an indicator matrix so the
  reductions ride the MXU/VPU (the FuseMax single pass — later tiles
  rescale earlier sums by ``exp(m_old - m_new)``). Deterministic forward
  only (eval / predict / the serving ladder), mirroring
  ``ops/pallas_encode.py``'s dropout discipline. On multi-device meshes
  it must be ``shard_map``-ped over the data axis — a ``pallas_call`` is
  opaque to GSPMD and would otherwise be replicated (same reasoning as
  ``ops/pallas_ce.py``).

VMEM at java14m serving shapes (per-shard segments Bs=1024, D=384,
SLOT_TILE=512, d=128): tile inputs ~0.8 MB, weights ~0.6 MB resident,
the (T, Bs) indicator + its two masked copies ~6 MB, the (D, Bs) f32
accumulator 1.5 MB — comfortably under the ~16 MB/core budget, and
independent of capacity (the grid scales instead).

Dense-path parity (``tests/test_pallas_ragged.py``): the dense encode
gives masked slots attention ``~e-30`` — zero at fp32 resolution — so
excluding them here matches to fp32 rounding; the one real divergence,
rows with ``count == 0`` (static-shape padding, weight 0), is fixed up
analytically (uniform ``1/C`` attention, ``code = x_pad``) to match the
dense path's finite-uniform behavior exactly. Dropout draws its keep
mask over the PACKED ``(shards, cap, 3d)`` layout rather than the dense
``(B, C, 3d)`` one — same keep probability, a different (still
deterministic, seed-keyed) stream, the ``DROPOUT_PRNG_IMPL='rbg'``
precedent.

Gated by ``Config.USE_PALLAS_RAGGED_FUSION`` (threaded through
models/backends.py and training/trainer.py) with the same
``tpu_backend_active()`` fallback discipline as the other kernels: off
TPU the jnp twin runs — never the interpreter.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from code2vec_tpu.ops._pallas_common import (PALLAS_AVAILABLE,
                                             tpu_backend_active)

if PALLAS_AVAILABLE:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.ops._shard_map import shard_map
from code2vec_tpu.parallel.mesh import DATA_AXIS

SLOT_TILE = 512     # packed slots per grid step; capacity pads to a multiple
_NEG = -1e30        # finite -inf stand-in (denormal-safe, like pallas_ce)


def _precision(dtype) -> jax.lax.Precision:
    """Mirror the dense encode: fp32 asks for true-fp32 MXU passes, bf16
    uses the fast path (models/functional.py::encode)."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


# ------------------------------------------------------------ jnp twin
def _stats_jnp(src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
               attn_vec, per_shard: int, precision):
    """Reference twin of the kernel: (scores, m, z, acc) via jnp segment
    ops on the shard-structured stream. Differentiable (the segment max
    is stop-gradiented — softmax is shift-invariant, so the gradient is
    exact) and GSPMD-partitionable along the leading shards axis."""
    shards, cap = seg.shape
    x = jnp.tanh(jnp.matmul(src_e, w_src, precision=precision)
                 + jnp.matmul(pth_e, w_path, precision=precision)
                 + jnp.matmul(tgt_e, w_tgt, precision=precision))
    scores = jnp.matmul(x, attn_vec,
                        precision=precision)[..., 0]         # (D, cap)
    scores = jnp.where(slot_valid, scores.astype(jnp.float32), _NEG)
    shard_idx = jnp.broadcast_to(
        jnp.arange(shards, dtype=jnp.int32)[:, None], (shards, cap))
    m = jnp.full((shards, per_shard), _NEG, jnp.float32)
    m = m.at[shard_idx, seg].max(scores, mode='drop')
    m = jax.lax.stop_gradient(m)
    p = jnp.exp(scores - jnp.take_along_axis(m, seg, axis=1))
    p = jnp.where(slot_valid, p, 0.0)                        # (D, cap)
    z = jnp.zeros((shards, per_shard), jnp.float32)
    z = z.at[shard_idx, seg].add(p, mode='drop')
    acc = jnp.zeros((shards, per_shard, x.shape[-1]), jnp.float32)
    acc = acc.at[shard_idx, seg].add(
        p[..., None] * x.astype(jnp.float32), mode='drop')
    return scores, m, z, acc


# -------------------------------------------------------- pallas kernel
def _ragged_kernel(precision, src_ref, pth_ref, tgt_ref, seg_ref, valid_ref,
                   wsrc_ref, wpath_ref, wtgt_ref, attn_ref,
                   scores_ref, m_out_ref, z_out_ref, acc_out_ref,
                   m_ref, z_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        z_ref[:] = jnp.zeros_like(z_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # encode: row-split transform + tanh + score, fp32 accumulation
    x = jnp.dot(src_ref[:], wsrc_ref[:], precision=precision,
                preferred_element_type=jnp.float32)
    x += jnp.dot(pth_ref[:], wpath_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)
    x += jnp.dot(tgt_ref[:], wtgt_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)
    x = jnp.tanh(x)                                          # (T, D) f32
    sc = jnp.dot(x, attn_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)         # (T, 1)
    valid = valid_ref[:] > 0.0                               # (T, 1)
    sc = jnp.where(valid, sc, _NEG)
    scores_ref[:] = sc

    # segment membership for this tile: a (T, n_seg) indicator so every
    # per-example reduction is one masked reduce / one MXU contraction
    n_seg = m_ref.shape[1]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (sc.shape[0], n_seg), 1)
    onehot_b = (seg_ref[:] == lanes) & valid                 # (T, n_seg)
    onehot = onehot_b.astype(jnp.float32)

    # FuseMax single pass: fold this tile's per-segment max into the
    # running max, rescale the running sums, accumulate the tile
    m_tile = jnp.max(jnp.where(onehot_b, sc, _NEG),
                     axis=0, keepdims=True)                  # (1, n_seg)
    m_new = jnp.maximum(m_ref[:], m_tile)
    corr = jnp.exp(m_ref[:] - m_new)                         # (1, n_seg)
    m_ref[:] = m_new
    m_slot = jnp.sum(onehot * m_new, axis=1, keepdims=True)  # (T, 1)
    p = jnp.where(valid, jnp.exp(sc - m_slot), 0.0)          # (T, 1)
    pz = onehot * p                                          # (T, n_seg)
    z_ref[:] = z_ref[:] * corr + jnp.sum(pz, axis=0, keepdims=True)
    # acc lives (D, n_seg) so the rescale broadcasts along rows and the
    # tile contraction is a single dot_general over the slot axis
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        x, pz, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # (D, n_seg)

    @pl.when(i == pl.num_programs(0) - 1)
    def _emit():
        m_out_ref[:] = m_ref[:]
        z_out_ref[:] = z_ref[:]
        acc_out_ref[:] = acc_ref[:]


def _stats_pallas(src_e, pth_e, tgt_e, seg, valid, w_src, w_path, w_tgt,
                  attn_vec, n_seg: int, interpret: bool, precision):
    """One shard's flat packed stream ``(cap, d)`` -> ``(scores (cap,),
    m (n_seg,), z (n_seg,), acc (n_seg, D))`` via the fused kernel."""
    cap, token_dim = src_e.shape
    path_dim = pth_e.shape[1]
    code_dim = w_src.shape[1]
    padded = -(-cap // SLOT_TILE) * SLOT_TILE
    pad = padded - cap
    if pad:
        src_e = jnp.pad(src_e, ((0, pad), (0, 0)))
        pth_e = jnp.pad(pth_e, ((0, pad), (0, 0)))
        tgt_e = jnp.pad(tgt_e, ((0, pad), (0, 0)))
        seg = jnp.pad(seg, (0, pad))
        valid = jnp.pad(valid, (0, pad))     # False: pad slots are inert
    seg2 = seg.reshape(padded, 1).astype(jnp.int32)
    valid2 = valid.reshape(padded, 1).astype(jnp.float32)
    grid = (padded // SLOT_TILE,)
    row_block = lambda dim: pl.BlockSpec((SLOT_TILE, dim),
                                         lambda i: (i, 0))
    full_block = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0))
    kernel = functools.partial(_ragged_kernel, precision)
    scores, m, z, acc = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            row_block(token_dim), row_block(path_dim), row_block(token_dim),
            row_block(1), row_block(1),
            full_block(w_src.shape), full_block(w_path.shape),
            full_block(w_tgt.shape), full_block(attn_vec.shape),
        ],
        out_specs=[
            row_block(1),
            full_block((1, n_seg)), full_block((1, n_seg)),
            full_block((code_dim, n_seg)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, n_seg), jnp.float32),
            jax.ShapeDtypeStruct((1, n_seg), jnp.float32),
            jax.ShapeDtypeStruct((code_dim, n_seg), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, n_seg), jnp.float32),       # running max
            pltpu.VMEM((1, n_seg), jnp.float32),       # running sumexp
            pltpu.VMEM((code_dim, n_seg), jnp.float32),  # weighted sum
        ],
        interpret=interpret,
    )(src_e, pth_e, tgt_e, seg2, valid2, w_src, w_path, w_tgt, attn_vec)
    return scores[:cap, 0], m[0], z[0], acc.T


def _stats_kernel_path(src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                       w_tgt, attn_vec, per_shard: int, mesh,
                       interpret: bool, precision):
    """Kernel stats over the shard-structured stream. With a multi-device
    mesh the per-shard kernel is shard_mapped over the data axis (a
    pallas_call is opaque to GSPMD); otherwise the shards collapse into
    one flat stream with globally-offset segment ids — one kernel call,
    one set of scratch accumulators."""
    shards, cap = seg.shape

    def one_shard(src_l, pth_l, tgt_l, seg_l, valid_l, ws, wp, wt, av):
        sc, m, z, acc = _stats_pallas(
            src_l[0], pth_l[0], tgt_l[0], seg_l[0], valid_l[0],
            ws, wp, wt, av, per_shard, interpret, precision)
        return (sc[None], m[None], z[None], acc[None])

    if mesh is not None and mesh.size > 1:
        # check_vma=False: outputs follow the data axis exactly like the
        # inputs, but the static checker can't see through the kernel
        # (same as ops/pallas_ce.py::_sharded_forward)
        return shard_map(
            one_shard, mesh=mesh,
            in_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                      P(DATA_AXIS, None, None), P(DATA_AXIS, None),
                      P(DATA_AXIS, None), P(None, None), P(None, None),
                      P(None, None), P(None, None)),
            out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None),
                       P(DATA_AXIS, None), P(DATA_AXIS, None, None)),
            check_vma=False)(src_e, pth_e, tgt_e, seg, slot_valid,
                             w_src, w_path, w_tgt, attn_vec)
    # single device: one flat stream, segment ids offset per shard
    flat = shards * cap
    offsets = (jnp.arange(shards, dtype=jnp.int32) * per_shard)[:, None]
    seg_flat = (seg + offsets).reshape(flat)
    sc, m, z, acc = _stats_pallas(
        src_e.reshape(flat, -1), pth_e.reshape(flat, -1),
        tgt_e.reshape(flat, -1), seg_flat, slot_valid.reshape(flat),
        w_src, w_path, w_tgt, attn_vec, shards * per_shard, interpret,
        precision)
    return (sc.reshape(shards, cap), m.reshape(shards, per_shard),
            z.reshape(shards, per_shard),
            acc.reshape(shards, per_shard, -1))


# ------------------------------------------------------------- finish
def _finish(scores, m, z, acc, seg, pos, slot_valid, count2, x_pad,
            max_contexts: int):
    """(stats, segment structure) -> (code_vectors (B, D) fp32, attention
    planes (B, C) fp32). The count == 0 fixups reproduce the dense
    path's finite-uniform behavior for all-padding rows exactly."""
    shards, per_shard = count2.shape
    cap = seg.shape[1]
    nonempty = count2 > 0                                    # (D, Bs)
    # guard empty segments' 0/0 (the fixup below overwrites them). NOT
    # jnp.maximum(z, 1.0): a single-valid-slot segment has z == 1.0
    # exactly (its max slot contributes exp(0)), and jax halves the
    # gradient of maximum at ties — which would silently halve those
    # rows' softmax-normalization gradient
    z_safe = jnp.where(nonempty, z, 1.0)
    code = acc / z_safe[..., None]
    code = jnp.where(nonempty[..., None], code,
                     x_pad.astype(jnp.float32)[None, None, :])
    p = jnp.exp(scores - jnp.take_along_axis(m, seg, axis=1))
    w = jnp.where(slot_valid,
                  p / jnp.take_along_axis(z_safe, seg, axis=1), 0.0)
    shard_idx = jnp.broadcast_to(
        jnp.arange(shards, dtype=jnp.int32)[:, None], (shards, cap))
    attn = jnp.zeros((shards, per_shard, max_contexts), jnp.float32)
    # capacity-pad slots carry w == 0 and positions past their example's
    # count, so add-with-drop can only write zeros onto tail columns
    attn = attn.at[shard_idx, seg, pos].add(w, mode='drop')
    attn = jnp.where(nonempty[..., None], attn, 1.0 / max_contexts)
    batch = shards * per_shard
    return code.reshape(batch, -1), attn.reshape(batch, max_contexts)


# --------------------------------------------------------------- entry
def ragged_encode(token_embedding: jax.Array, path_embedding: jax.Array,
                  transform: jax.Array, attention: jax.Array,
                  ctx: jax.Array, count: jax.Array, *,
                  max_contexts: int, token_pad: int, path_pad: int,
                  dtype: jnp.dtype = jnp.float32,
                  dropout_rng: Optional[jax.Array] = None,
                  dropout_keep_rate: float = 1.0,
                  dropout_prng_impl: str = 'threefry2x32',
                  embed_grad_impl: str = 'dense',
                  use_kernel: Optional[bool] = None,
                  interpret: Optional[bool] = None,
                  mesh=None) -> Tuple[jax.Array, jax.Array]:
    """Packed wire arrays -> (code_vectors (B, D) fp32, attention planes
    (B, C) fp32), with no ``(B, C, .)`` intermediate anywhere.

    ``use_kernel`` None routes the Pallas kernel iff a real TPU backend
    is active AND no dropout applies (the kernel is forward-only); False
    forces the jnp twin; True forces the kernel (tests run it with
    ``interpret=True`` on CPU). ``mesh`` shard_maps the kernel over the
    data axis on multi-device meshes; the twin ignores it (its segment
    ops partition under GSPMD by the leading shards axis).
    """
    shards, cap, _ = ctx.shape
    batch = count.shape[0]
    per_shard = batch // shards
    count2 = count.reshape(shards, per_shard).astype(jnp.int32)
    # THE segment arithmetic, shared with unpack_device (data/packed.py)
    # so the parity-critical slot->example mapping has one definition
    from code2vec_tpu.data.packed import segment_structure
    seg, pos, in_range = segment_structure(count2, cap)
    src, pth, tgt = ctx[..., 0], ctx[..., 1], ctx[..., 2]
    # the reader.context_valid_mask predicate, applied on the packed
    # stream: interior holes (all three parts PAD) drop out here exactly
    # as the dense path's log-mask drops them out of its softmax
    slot_valid = in_range & ((src != token_pad) | (tgt != token_pad)
                             | (pth != path_pad))            # (D, cap)

    apply_dropout = dropout_rng is not None and dropout_keep_rate < 1.0
    if use_kernel is None:
        use_kernel = (PALLAS_AVAILABLE and tpu_backend_active()
                      and not apply_dropout)
    if use_kernel and apply_dropout:
        raise ValueError(
            'the Pallas ragged kernel serves the deterministic forward '
            'only; dropout routes through the jnp twin (pass '
            'use_kernel=False or no dropout_rng)')
    if interpret is None:
        interpret = not tpu_backend_active()

    from code2vec_tpu.ops.embed_grad import take_rows
    src_e = take_rows(token_embedding, src,
                      impl=embed_grad_impl).astype(dtype)    # (D, cap, d)
    pth_e = take_rows(path_embedding, pth,
                      impl=embed_grad_impl).astype(dtype)
    tgt_e = take_rows(token_embedding, tgt,
                      impl=embed_grad_impl).astype(dtype)
    token_dim = src_e.shape[-1]
    path_dim = pth_e.shape[-1]

    if apply_dropout:
        # THE shared PRNG routing (models/functional.py::
        # dropout_keep_mask — lazy import; functional's import of this
        # module is deferred, so there is no cycle). The draw is over
        # retained slots only: the packed layout also SHRINKS the mask
        # draw by the fill factor
        from code2vec_tpu.models.functional import dropout_keep_mask
        keep = dropout_keep_mask(dropout_rng, dropout_keep_rate,
                                 (shards, cap, 2 * token_dim + path_dim),
                                 dropout_prng_impl)

        def drop(e, lo, hi):
            return jnp.where(keep[..., lo:hi], e / dropout_keep_rate,
                             jnp.zeros_like(e))
        src_e = drop(src_e, 0, token_dim)
        pth_e = drop(pth_e, token_dim, token_dim + path_dim)
        tgt_e = drop(tgt_e, token_dim + path_dim,
                     2 * token_dim + path_dim)

    t = transform.astype(dtype)
    w_src = t[:token_dim]
    w_path = t[token_dim:token_dim + path_dim]
    w_tgt = t[token_dim + path_dim:]
    attn_vec = attention.astype(dtype)                       # (D, 1)
    precision = _precision(dtype)

    # the dense path's value for every all-PAD slot — the analytic
    # stand-in for count == 0 rows (deterministic: such rows carry
    # weight 0, so dropout on them is loss-invisible either way)
    pad_ctx = jnp.concatenate([
        token_embedding[token_pad], path_embedding[path_pad],
        token_embedding[token_pad]]).astype(dtype)
    x_pad = jnp.tanh(jnp.matmul(pad_ctx[None, :], t,
                                precision=precision))[0]     # (D,)

    if use_kernel:
        scores, m, z, acc = _stats_kernel_path(
            src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
            attn_vec, per_shard, mesh, interpret, precision)
    else:
        scores, m, z, acc = _stats_jnp(
            src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
            attn_vec, per_shard, precision)
    return _finish(scores, m, z, acc, seg, pos, slot_valid, count2,
                   x_pad, max_contexts)
