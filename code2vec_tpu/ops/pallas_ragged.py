"""Ragged fused encode + attention straight off the packed wire.

The packed wire format (data/packed.py) ships each batch as per-shard
dense ``(data_shards, capacity, 3)`` context triples plus per-example
``count``s. Until now every consumer paid a full ``(B, max_contexts)``
segment-scatter (``unpack_device``) back to plane layout BEFORE the
encoder ran — at the java14m fill rate (contexts/method p50 28 of 200)
that materializes ~4x more context slots than the batch actually holds,
and the dense encode then spends FLOPs and HBM traffic on every one of
them. This module walks the packed segments directly instead:

  per slot t of the packed stream (slots past a shard's total and
  interior all-PAD holes are masked OUT, matching the dense path's
  ``log(1e-30)`` masking to fp32 rounding):

    e_t = [tok[src_t] ; path[pth_t] ; tok[tgt_t]]            gather
    x_t = tanh(e_t @ TRANSFORM)        (row-split, no concat) encode
    s_t = x_t . ATTENTION                                     score

  per example i (a SEGMENT of the stream, delimited by ``count``):

    m_i  = max_t s_t                 \\  single-pass max-sum softmax
    z_i  = sum_t exp(s_t - m_i)       |  (FuseMax, arxiv 2406.10491):
    c_i  = sum_t exp(s_t - m_i) x_t  /   one walk, no separate sweeps
    code_i = c_i / z_i

Two interchangeable implementations produce the same ``(scores, m, z,
acc)`` statistics:

- ``_stats_jnp`` — the reference twin: plain jnp segment ops (scatter
  max/add over the shard-structured stream), fully differentiable, runs
  everywhere and partitions under GSPMD (leading data_shards axis, like
  ``unpack_device``).
- ``_stats_pallas`` — the Pallas TPU kernel: one grid walk over slot
  tiles with the per-example running ``(m, z, acc)`` resident in VMEM,
  segment membership resolved per tile with an indicator matrix so the
  reductions ride the MXU/VPU (the FuseMax single pass — later tiles
  rescale earlier sums by ``exp(m_old - m_new)``). On multi-device
  meshes it must be ``shard_map``-ped over the data axis — a
  ``pallas_call`` is opaque to GSPMD and would otherwise be replicated
  (same reasoning as ``ops/pallas_ce.py``).

TRAIN path (``ragged_encode_code``, the custom VJP): the code-vector
encode is wrapped in ``jax.custom_vjp`` so the backward never stores a
per-slot residual. The forward saves only the per-example softmax stats
``(m, z)``, the ``(B, D)`` code vectors, and the inputs it was handed
(indices + params + the dropout PRNG key); the backward re-gathers the
embeddings, re-draws the SAME dropout mask from the threaded key, and
recomputes ``x``/``scores``/``w`` per slot tile — the FuseMax
recompute-over-store schedule — before emitting exact softmax-backward
gradients: TRANSFORM/ATTENTION densely (per-tile MXU accumulation) and
the token/path table gradients as segment scatter-adds through
``ops/embed_grad.table_grad`` (so ``EMBED_GRAD_IMPL`` and the lazy-Adam
sparse-row substrate compose). The ``(D, cap, 3d)`` gathered context
embeddings and the ``(D, cap, D)`` activations exist only transiently
inside each pass, never as residuals between them — the autodiff twin
saved all of them (tests/test_pallas_ragged.py asserts the residual set
via the vjp closure). Like the forward, the backward has two
implementations sharing one contract: a jnp twin (CPU/fallback — the
residual win applies there too) and a second Pallas kernel walking the
same packed ``(D, cap, 3)`` segments (``_bwd_kernel``), gated on-chip by
``Config.RAGGED_TRAIN_KERNEL`` pending the >=2% flip rule
(scripts/flip_verdict.py). Dropout now rides BOTH implementations: the
keep mask is drawn over the packed ``(shards, cap, 3d)`` layout outside
the kernels and applied to their embedding inputs, so the fused train
draw bit-matches the jnp twin's draw by construction.

VMEM at java14m serving shapes (per-shard segments Bs=1024, D=384,
SLOT_TILE=512, d=128): tile inputs ~0.8 MB, weights ~0.6 MB resident,
the (T, Bs) indicator + its two masked copies ~6 MB, the (D, Bs) f32
accumulator 1.5 MB — comfortably under the ~16 MB/core budget, and
independent of capacity (the grid scales instead).

Dense-path parity (``tests/test_pallas_ragged.py``): the dense encode
gives masked slots attention ``~e-30`` — zero at fp32 resolution — so
excluding them here matches to fp32 rounding; the one real divergence,
rows with ``count == 0`` (static-shape padding, weight 0), is fixed up
analytically (uniform ``1/C`` attention, ``code = x_pad``) to match the
dense path's finite-uniform behavior exactly. Dropout draws its keep
mask over the PACKED ``(shards, cap, 3d)`` layout rather than the dense
``(B, C, 3d)`` one — same keep probability, a different (still
deterministic, seed-keyed) stream, the ``DROPOUT_PRNG_IMPL='rbg'``
precedent.

Gated by ``Config.USE_PALLAS_RAGGED_FUSION`` (threaded through
models/backends.py and training/trainer.py) with the same
``tpu_backend_active()`` fallback discipline as the other kernels: off
TPU the jnp twin runs — never the interpreter.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from code2vec_tpu.ops._pallas_common import (PALLAS_AVAILABLE,
                                             tpu_backend_active)

if PALLAS_AVAILABLE:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.ops._shard_map import shard_map
from code2vec_tpu.parallel.mesh import DATA_AXIS

SLOT_TILE = 512     # packed slots per grid step; capacity pads to a multiple
_NEG = -1e30        # finite -inf stand-in (denormal-safe, like pallas_ce)


def _precision(dtype) -> jax.lax.Precision:
    """Mirror the dense encode: fp32 asks for true-fp32 MXU passes, bf16
    uses the fast path (models/functional.py::encode)."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


# ------------------------------------------------------------ jnp twin
def _stats_jnp(src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
               attn_vec, per_shard: int, precision):
    """Reference twin of the kernel: (scores, m, z, acc) via jnp segment
    ops on the shard-structured stream. Differentiable (the segment max
    is stop-gradiented — softmax is shift-invariant, so the gradient is
    exact) and GSPMD-partitionable along the leading shards axis."""
    shards, cap = seg.shape
    x = jnp.tanh(jnp.matmul(src_e, w_src, precision=precision)
                 + jnp.matmul(pth_e, w_path, precision=precision)
                 + jnp.matmul(tgt_e, w_tgt, precision=precision))
    scores = jnp.matmul(x, attn_vec,
                        precision=precision)[..., 0]         # (D, cap)
    scores = jnp.where(slot_valid, scores.astype(jnp.float32), _NEG)
    shard_idx = jnp.broadcast_to(
        jnp.arange(shards, dtype=jnp.int32)[:, None], (shards, cap))
    m = jnp.full((shards, per_shard), _NEG, jnp.float32)
    m = m.at[shard_idx, seg].max(scores, mode='drop')
    m = jax.lax.stop_gradient(m)
    p = jnp.exp(scores - jnp.take_along_axis(m, seg, axis=1))
    p = jnp.where(slot_valid, p, 0.0)                        # (D, cap)
    z = jnp.zeros((shards, per_shard), jnp.float32)
    z = z.at[shard_idx, seg].add(p, mode='drop')
    acc = jnp.zeros((shards, per_shard, x.shape[-1]), jnp.float32)
    acc = acc.at[shard_idx, seg].add(
        p[..., None] * x.astype(jnp.float32), mode='drop')
    return scores, m, z, acc


# -------------------------------------------------------- pallas kernel
def _ragged_kernel(precision, src_ref, pth_ref, tgt_ref, seg_ref, valid_ref,
                   wsrc_ref, wpath_ref, wtgt_ref, attn_ref,
                   scores_ref, m_out_ref, z_out_ref, acc_out_ref,
                   m_ref, z_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        z_ref[:] = jnp.zeros_like(z_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # encode: row-split transform + tanh + score, fp32 accumulation
    x = jnp.dot(src_ref[:], wsrc_ref[:], precision=precision,
                preferred_element_type=jnp.float32)
    x += jnp.dot(pth_ref[:], wpath_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)
    x += jnp.dot(tgt_ref[:], wtgt_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)
    x = jnp.tanh(x)                                          # (T, D) f32
    sc = jnp.dot(x, attn_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)         # (T, 1)
    valid = valid_ref[:] > 0.0                               # (T, 1)
    sc = jnp.where(valid, sc, _NEG)
    scores_ref[:] = sc

    # segment membership for this tile: a (T, n_seg) indicator so every
    # per-example reduction is one masked reduce / one MXU contraction
    n_seg = m_ref.shape[1]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (sc.shape[0], n_seg), 1)
    onehot_b = (seg_ref[:] == lanes) & valid                 # (T, n_seg)
    onehot = onehot_b.astype(jnp.float32)

    # FuseMax single pass: fold this tile's per-segment max into the
    # running max, rescale the running sums, accumulate the tile
    m_tile = jnp.max(jnp.where(onehot_b, sc, _NEG),
                     axis=0, keepdims=True)                  # (1, n_seg)
    m_new = jnp.maximum(m_ref[:], m_tile)
    corr = jnp.exp(m_ref[:] - m_new)                         # (1, n_seg)
    m_ref[:] = m_new
    m_slot = jnp.sum(onehot * m_new, axis=1, keepdims=True)  # (T, 1)
    p = jnp.where(valid, jnp.exp(sc - m_slot), 0.0)          # (T, 1)
    pz = onehot * p                                          # (T, n_seg)
    z_ref[:] = z_ref[:] * corr + jnp.sum(pz, axis=0, keepdims=True)
    # acc lives (D, n_seg) so the rescale broadcasts along rows and the
    # tile contraction is a single dot_general over the slot axis
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        x, pz, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # (D, n_seg)

    @pl.when(i == pl.num_programs(0) - 1)
    def _emit():
        m_out_ref[:] = m_ref[:]
        z_out_ref[:] = z_ref[:]
        acc_out_ref[:] = acc_ref[:]


def _stats_pallas(src_e, pth_e, tgt_e, seg, valid, w_src, w_path, w_tgt,
                  attn_vec, n_seg: int, interpret: bool, precision):
    """One shard's flat packed stream ``(cap, d)`` -> ``(scores (cap,),
    m (n_seg,), z (n_seg,), acc (n_seg, D))`` via the fused kernel."""
    cap, token_dim = src_e.shape
    path_dim = pth_e.shape[1]
    code_dim = w_src.shape[1]
    padded = -(-cap // SLOT_TILE) * SLOT_TILE
    pad = padded - cap
    if pad:
        src_e = jnp.pad(src_e, ((0, pad), (0, 0)))
        pth_e = jnp.pad(pth_e, ((0, pad), (0, 0)))
        tgt_e = jnp.pad(tgt_e, ((0, pad), (0, 0)))
        seg = jnp.pad(seg, (0, pad))
        valid = jnp.pad(valid, (0, pad))     # False: pad slots are inert
    seg2 = seg.reshape(padded, 1).astype(jnp.int32)
    valid2 = valid.reshape(padded, 1).astype(jnp.float32)
    grid = (padded // SLOT_TILE,)
    row_block = lambda dim: pl.BlockSpec((SLOT_TILE, dim),
                                         lambda i: (i, 0))
    full_block = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0))
    kernel = functools.partial(_ragged_kernel, precision)
    scores, m, z, acc = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            row_block(token_dim), row_block(path_dim), row_block(token_dim),
            row_block(1), row_block(1),
            full_block(w_src.shape), full_block(w_path.shape),
            full_block(w_tgt.shape), full_block(attn_vec.shape),
        ],
        out_specs=[
            row_block(1),
            full_block((1, n_seg)), full_block((1, n_seg)),
            full_block((code_dim, n_seg)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, n_seg), jnp.float32),
            jax.ShapeDtypeStruct((1, n_seg), jnp.float32),
            jax.ShapeDtypeStruct((code_dim, n_seg), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, n_seg), jnp.float32),       # running max
            pltpu.VMEM((1, n_seg), jnp.float32),       # running sumexp
            pltpu.VMEM((code_dim, n_seg), jnp.float32),  # weighted sum
        ],
        interpret=interpret,
    )(src_e, pth_e, tgt_e, seg2, valid2, w_src, w_path, w_tgt, attn_vec)
    return scores[:cap, 0], m[0], z[0], acc.T


def _stats_kernel_path(src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                       w_tgt, attn_vec, per_shard: int, mesh,
                       interpret: bool, precision):
    """Kernel stats over the shard-structured stream. With a multi-device
    mesh the per-shard kernel is shard_mapped over the data axis (a
    pallas_call is opaque to GSPMD); otherwise the shards collapse into
    one flat stream with globally-offset segment ids — one kernel call,
    one set of scratch accumulators."""
    shards, cap = seg.shape

    def one_shard(src_l, pth_l, tgt_l, seg_l, valid_l, ws, wp, wt, av):
        sc, m, z, acc = _stats_pallas(
            src_l[0], pth_l[0], tgt_l[0], seg_l[0], valid_l[0],
            ws, wp, wt, av, per_shard, interpret, precision)
        return (sc[None], m[None], z[None], acc[None])

    if mesh is not None and mesh.size > 1:
        # check_vma=False: outputs follow the data axis exactly like the
        # inputs, but the static checker can't see through the kernel
        # (same as ops/pallas_ce.py::_sharded_forward)
        return shard_map(
            one_shard, mesh=mesh,
            in_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                      P(DATA_AXIS, None, None), P(DATA_AXIS, None),
                      P(DATA_AXIS, None), P(None, None), P(None, None),
                      P(None, None), P(None, None)),
            out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None),
                       P(DATA_AXIS, None), P(DATA_AXIS, None, None)),
            check_vma=False)(src_e, pth_e, tgt_e, seg, slot_valid,
                             w_src, w_path, w_tgt, attn_vec)
    # single device: one flat stream, segment ids offset per shard
    flat = shards * cap
    offsets = (jnp.arange(shards, dtype=jnp.int32) * per_shard)[:, None]
    seg_flat = (seg + offsets).reshape(flat)
    sc, m, z, acc = _stats_pallas(
        src_e.reshape(flat, -1), pth_e.reshape(flat, -1),
        tgt_e.reshape(flat, -1), seg_flat, slot_valid.reshape(flat),
        w_src, w_path, w_tgt, attn_vec, shards * per_shard, interpret,
        precision)
    return (sc.reshape(shards, cap), m.reshape(shards, per_shard),
            z.reshape(shards, per_shard),
            acc.reshape(shards, per_shard, -1))


# ------------------------------------------------------------- finish
def _code_from_stats(z, acc, count2, x_pad):
    """(z, acc) stats -> (D, Bs, Dc) fp32 code vectors, with the
    count == 0 analytic fixup (code = x_pad)."""
    nonempty = count2 > 0                                    # (D, Bs)
    # guard empty segments' 0/0 (the fixup below overwrites them). NOT
    # jnp.maximum(z, 1.0): a single-valid-slot segment has z == 1.0
    # exactly (its max slot contributes exp(0)), and jax halves the
    # gradient of maximum at ties — which would silently halve those
    # rows' softmax-normalization gradient
    z_safe = jnp.where(nonempty, z, 1.0)
    code = acc / z_safe[..., None]
    return jnp.where(nonempty[..., None], code,
                     x_pad.astype(jnp.float32)[None, None, :])


def _finish(scores, m, z, acc, seg, pos, slot_valid, count2, x_pad,
            max_contexts: int):
    """(stats, segment structure) -> (code_vectors (B, D) fp32, attention
    planes (B, C) fp32). The count == 0 fixups reproduce the dense
    path's finite-uniform behavior for all-padding rows exactly."""
    shards, per_shard = count2.shape
    cap = seg.shape[1]
    nonempty = count2 > 0                                    # (D, Bs)
    z_safe = jnp.where(nonempty, z, 1.0)
    code = _code_from_stats(z, acc, count2, x_pad)
    p = jnp.exp(scores - jnp.take_along_axis(m, seg, axis=1))
    w = jnp.where(slot_valid,
                  p / jnp.take_along_axis(z_safe, seg, axis=1), 0.0)
    shard_idx = jnp.broadcast_to(
        jnp.arange(shards, dtype=jnp.int32)[:, None], (shards, cap))
    attn = jnp.zeros((shards, per_shard, max_contexts), jnp.float32)
    # capacity-pad slots carry w == 0 and positions past their example's
    # count, so add-with-drop can only write zeros onto tail columns
    attn = attn.at[shard_idx, seg, pos].add(w, mode='drop')
    attn = jnp.where(nonempty[..., None], attn, 1.0 / max_contexts)
    batch = shards * per_shard
    return code.reshape(batch, -1), attn.reshape(batch, max_contexts)


# ------------------------------------------------- shared preparation
def _segment_inputs(ctx, count, token_pad: int, path_pad: int):
    """Packed wire arrays -> the segment structure + index planes every
    pass (forward AND recompute-backward) derives identically."""
    from code2vec_tpu.data.packed import segment_structure
    shards, cap, _ = ctx.shape
    per_shard = count.shape[0] // shards
    count2 = count.reshape(shards, per_shard).astype(jnp.int32)
    seg, pos, in_range = segment_structure(count2, cap)
    src, pth, tgt = ctx[..., 0], ctx[..., 1], ctx[..., 2]
    # the reader.context_valid_mask predicate, applied on the packed
    # stream: interior holes (all three parts PAD) drop out here exactly
    # as the dense path's log-mask drops them out of its softmax
    slot_valid = in_range & ((src != token_pad) | (tgt != token_pad)
                             | (pth != path_pad))            # (D, cap)
    return count2, seg, pos, slot_valid, src, pth, tgt


def _dropout_parts(dropout_rng, dropout_keep_rate: float,
                   dropout_prng_impl: str, shards: int, cap: int,
                   token_dim: int, path_dim: int):
    """The packed-layout keep mask, split per embedding part — THE one
    draw both the forward and the recompute backward make from the
    threaded key, so fused-vs-twin and fwd-vs-bwd masks bit-match by
    construction (models/functional.py::dropout_keep_mask routing)."""
    from code2vec_tpu.models.functional import dropout_keep_mask
    keep = dropout_keep_mask(dropout_rng, dropout_keep_rate,
                             (shards, cap, 2 * token_dim + path_dim),
                             dropout_prng_impl)
    return (keep[..., :token_dim],
            keep[..., token_dim:token_dim + path_dim],
            keep[..., token_dim + path_dim:])


def _apply_keep(e, keep, keep_rate: float):
    return jnp.where(keep, e / keep_rate, jnp.zeros_like(e))


def _split_weights(transform, attention, token_dim: int, path_dim: int,
                   dtype):
    t = transform.astype(dtype)
    return (t[:token_dim], t[token_dim:token_dim + path_dim],
            t[token_dim + path_dim:], attention.astype(dtype))


def _pad_forward(token_embedding, path_embedding, transform,
                 token_pad: int, path_pad: int, dtype, precision):
    """(pad_ctx (3d,), x_pad (Dc,)) — the dense path's value for every
    all-PAD slot, the analytic stand-in for count == 0 rows. No dropout
    (such rows carry weight 0, so dropout on them is loss-invisible)."""
    pad_ctx = jnp.concatenate([
        token_embedding[token_pad], path_embedding[path_pad],
        token_embedding[token_pad]]).astype(dtype)
    x_pad = jnp.tanh(jnp.matmul(pad_ctx[None, :], transform.astype(dtype),
                                precision=precision))[0]     # (Dc,)
    return pad_ctx, x_pad


# --------------------------------------------------------------- entry
def ragged_encode(token_embedding: jax.Array, path_embedding: jax.Array,
                  transform: jax.Array, attention: jax.Array,
                  ctx: jax.Array, count: jax.Array, *,
                  max_contexts: int, token_pad: int, path_pad: int,
                  dtype: jnp.dtype = jnp.float32,
                  dropout_rng: Optional[jax.Array] = None,
                  dropout_keep_rate: float = 1.0,
                  dropout_prng_impl: str = 'threefry2x32',
                  embed_grad_impl: str = 'dense',
                  use_kernel: Optional[bool] = None,
                  interpret: Optional[bool] = None,
                  mesh=None) -> Tuple[jax.Array, jax.Array]:
    """Packed wire arrays -> (code_vectors (B, D) fp32, attention planes
    (B, C) fp32), with no ``(B, C, .)`` intermediate anywhere.

    ``use_kernel`` None routes the Pallas kernel iff a real TPU backend
    is active; False forces the jnp twin; True forces the kernel (tests
    run it with ``interpret=True`` on CPU). Dropout (the fused TRAIN
    draw) rides either implementation: the packed-layout keep mask is
    applied to the gathered embeddings BEFORE the stats pass, so the
    kernel and the twin consume bit-identical inputs. NB the kernel
    itself is still not reverse-differentiable — training routes
    through :func:`ragged_encode_code`, whose custom VJP recomputes.
    ``mesh`` shard_maps the kernel over the data axis on multi-device
    meshes; the twin ignores it (its segment ops partition under GSPMD
    by the leading shards axis).
    """
    shards, cap, _ = ctx.shape
    batch = count.shape[0]
    per_shard = batch // shards
    # THE segment arithmetic, shared with unpack_device (data/packed.py)
    # so the parity-critical slot->example mapping has one definition
    count2, seg, pos, slot_valid, src, pth, tgt = _segment_inputs(
        ctx, count, token_pad, path_pad)

    apply_dropout = dropout_rng is not None and dropout_keep_rate < 1.0
    if use_kernel is None:
        use_kernel = PALLAS_AVAILABLE and tpu_backend_active()
    if interpret is None:
        interpret = not tpu_backend_active()

    from code2vec_tpu.ops.embed_grad import take_rows
    src_e = take_rows(token_embedding, src,
                      impl=embed_grad_impl).astype(dtype)    # (D, cap, d)
    pth_e = take_rows(path_embedding, pth,
                      impl=embed_grad_impl).astype(dtype)
    tgt_e = take_rows(token_embedding, tgt,
                      impl=embed_grad_impl).astype(dtype)
    token_dim = src_e.shape[-1]
    path_dim = pth_e.shape[-1]

    if apply_dropout:
        # THE shared PRNG routing (models/functional.py::
        # dropout_keep_mask via _dropout_parts — lazy import;
        # functional's import of this module is deferred, so there is
        # no cycle). The draw is over retained slots only: the packed
        # layout also SHRINKS the mask draw by the fill factor
        keep_src, keep_pth, keep_tgt = _dropout_parts(
            dropout_rng, dropout_keep_rate, dropout_prng_impl,
            shards, cap, token_dim, path_dim)
        src_e = _apply_keep(src_e, keep_src, dropout_keep_rate)
        pth_e = _apply_keep(pth_e, keep_pth, dropout_keep_rate)
        tgt_e = _apply_keep(tgt_e, keep_tgt, dropout_keep_rate)

    w_src, w_path, w_tgt, attn_vec = _split_weights(
        transform, attention, token_dim, path_dim, dtype)
    precision = _precision(dtype)
    _pad_ctx, x_pad = _pad_forward(token_embedding, path_embedding,
                                   transform, token_pad, path_pad, dtype,
                                   precision)

    if use_kernel:
        scores, m, z, acc = _stats_kernel_path(
            src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
            attn_vec, per_shard, mesh, interpret, precision)
    else:
        scores, m, z, acc = _stats_jnp(
            src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
            attn_vec, per_shard, precision)
    return _finish(scores, m, z, acc, seg, pos, slot_valid, count2,
                   x_pad, max_contexts)


# ------------------------------------------------- recompute backward
def _bwd_kernel(precision, src_ref, pth_ref, tgt_ref, seg_ref, valid_ref,
                wsrc_ref, wpath_ref, wtgt_ref, attn_row_ref,
                m_ref, z_ref, gc_ref, g_ref,
                de_src_ref, de_pth_ref, de_tgt_ref,
                dw_src_ref, dw_pth_ref, dw_tgt_ref, dattn_ref):
    """The second Pallas kernel: exact softmax-backward gradients off
    the SAME packed slot tiles the forward walked, with the per-slot
    state (x, scores, softmax weights) RECOMPUTED from the saved
    per-example ``(m, z)`` — recompute-over-store, so the forward never
    banks a ``(D, cap, .)`` residual. Per-slot cotangent streams
    (``de_*``) are emitted per tile; the dense TRANSFORM/ATTENTION
    gradients accumulate in the output blocks across grid steps (same
    index map every step keeps them VMEM-resident)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_src_ref[:] = jnp.zeros_like(dw_src_ref)
        dw_pth_ref[:] = jnp.zeros_like(dw_pth_ref)
        dw_tgt_ref[:] = jnp.zeros_like(dw_tgt_ref)
        dattn_ref[:] = jnp.zeros_like(dattn_ref)

    # recompute this tile's forward state
    x = jnp.dot(src_ref[:], wsrc_ref[:], precision=precision,
                preferred_element_type=jnp.float32)
    x += jnp.dot(pth_ref[:], wpath_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)
    x += jnp.dot(tgt_ref[:], wtgt_ref[:], precision=precision,
                 preferred_element_type=jnp.float32)
    x = jnp.tanh(x)                                          # (T, Dc) f32
    attn_row = attn_row_ref[:]                               # (1, Dc)
    sc = jax.lax.dot_general(x, attn_row, (((1,), (1,)), ((), ())),
                             precision=precision,
                             preferred_element_type=jnp.float32)  # (T, 1)
    valid = valid_ref[:] > 0.0                               # (T, 1)
    n_seg = m_ref.shape[1]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (sc.shape[0], n_seg), 1)
    onehot = ((seg_ref[:] == lanes) & valid).astype(jnp.float32)
    # per-slot views of the per-example stats/cotangents, via the same
    # indicator contraction the forward used (MXU/VPU, no gathers)
    m_slot = jnp.sum(onehot * m_ref[:], axis=1, keepdims=True)
    z_slot = jnp.sum(onehot * z_ref[:], axis=1, keepdims=True)
    gc_slot = jnp.sum(onehot * gc_ref[:], axis=1, keepdims=True)
    g_slot = jnp.dot(onehot, g_ref[:],
                     preferred_element_type=jnp.float32)     # (T, Dc)
    p = jnp.where(valid, jnp.exp(sc - m_slot), 0.0)
    w = p / jnp.where(z_slot > 0.0, z_slot, 1.0)             # (T, 1)
    # exact softmax backward (the stop-gradiented running max drops out:
    # softmax is shift-invariant)
    gdot = jnp.sum(x * g_slot, axis=1, keepdims=True)        # (T, 1)
    ds = w * (gdot - gc_slot)                                # (T, 1)
    dx = w * g_slot + ds * attn_row.astype(jnp.float32)
    du = (1.0 - x * x) * dx                                  # (T, Dc) f32
    de_src_ref[:] = jax.lax.dot_general(
        du, wsrc_ref[:], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)
    de_pth_ref[:] = jax.lax.dot_general(
        du, wpath_ref[:], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)
    de_tgt_ref[:] = jax.lax.dot_general(
        du, wtgt_ref[:], (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)
    dw_src_ref[:] += jax.lax.dot_general(
        src_ref[:], du, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)
    dw_pth_ref[:] += jax.lax.dot_general(
        pth_ref[:], du, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)
    dw_tgt_ref[:] += jax.lax.dot_general(
        tgt_ref[:], du, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)
    dattn_ref[:] += jax.lax.dot_general(
        x, ds, (((0,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32)                  # (Dc, 1)


def _grads_pallas(src_e, pth_e, tgt_e, seg, valid, w_src, w_path, w_tgt,
                  attn_vec, m, z, gc, g, n_seg: int, interpret: bool,
                  precision):
    """One shard's flat packed stream + saved ``(m, z)`` stats +
    per-example cotangents ``g`` (n_seg, Dc) / ``gc`` (n_seg,) ->
    (de_src/de_pth/de_tgt (cap, d) f32, dw_src/dw_pth/dw_tgt (d, Dc)
    f32, d_attn (Dc, 1) f32) via the recompute backward kernel."""
    cap, token_dim = src_e.shape
    path_dim = pth_e.shape[1]
    code_dim = w_src.shape[1]
    padded = -(-cap // SLOT_TILE) * SLOT_TILE
    pad = padded - cap
    if pad:
        src_e = jnp.pad(src_e, ((0, pad), (0, 0)))
        pth_e = jnp.pad(pth_e, ((0, pad), (0, 0)))
        tgt_e = jnp.pad(tgt_e, ((0, pad), (0, 0)))
        seg = jnp.pad(seg, (0, pad))
        valid = jnp.pad(valid, (0, pad))     # False: pad slots are inert
    seg2 = seg.reshape(padded, 1).astype(jnp.int32)
    valid2 = valid.reshape(padded, 1).astype(jnp.float32)
    attn_row = attn_vec.reshape(1, code_dim)
    grid = (padded // SLOT_TILE,)
    row_block = lambda dim: pl.BlockSpec((SLOT_TILE, dim),
                                         lambda i: (i, 0))
    full_block = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0))
    kernel = functools.partial(_bwd_kernel, precision)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            row_block(token_dim), row_block(path_dim), row_block(token_dim),
            row_block(1), row_block(1),
            full_block(w_src.shape), full_block(w_path.shape),
            full_block(w_tgt.shape), full_block((1, code_dim)),
            full_block((1, n_seg)), full_block((1, n_seg)),
            full_block((1, n_seg)), full_block((n_seg, code_dim)),
        ],
        out_specs=[
            row_block(token_dim), row_block(path_dim), row_block(token_dim),
            full_block((token_dim, code_dim)),
            full_block((path_dim, code_dim)),
            full_block((token_dim, code_dim)),
            full_block((code_dim, 1)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded, token_dim), jnp.float32),
            jax.ShapeDtypeStruct((padded, path_dim), jnp.float32),
            jax.ShapeDtypeStruct((padded, token_dim), jnp.float32),
            jax.ShapeDtypeStruct((token_dim, code_dim), jnp.float32),
            jax.ShapeDtypeStruct((path_dim, code_dim), jnp.float32),
            jax.ShapeDtypeStruct((token_dim, code_dim), jnp.float32),
            jax.ShapeDtypeStruct((code_dim, 1), jnp.float32),
        ],
        interpret=interpret,
    )(src_e, pth_e, tgt_e, seg2, valid2, w_src, w_path, w_tgt, attn_row,
      m.reshape(1, n_seg).astype(jnp.float32),
      z.reshape(1, n_seg).astype(jnp.float32),
      gc.reshape(1, n_seg).astype(jnp.float32),
      g.astype(jnp.float32))
    de_src, de_pth, de_tgt, dw_src, dw_pth, dw_tgt, d_attn = outs
    return (de_src[:cap], de_pth[:cap], de_tgt[:cap],
            dw_src, dw_pth, dw_tgt, d_attn)


def _grads_kernel_path(src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                       w_tgt, attn_vec, m, z, gc, g2, per_shard: int, mesh,
                       interpret: bool, precision):
    """Kernel backward over the shard-structured stream — the
    _stats_kernel_path discipline: shard_mapped over the data axis on
    multi-device meshes (pallas_call is opaque to GSPMD), one flat
    stream with offset segment ids on a single device. Returns
    (de_src/de_pth/de_tgt (D, cap, d) f32, dw parts (d, Dc) f32,
    d_attn (Dc, 1) f32), the dense parts summed over shards."""
    shards, cap = seg.shape

    def one_shard(src_l, pth_l, tgt_l, seg_l, valid_l, m_l, z_l, gc_l,
                  g_l, ws, wp, wt, av):
        outs = _grads_pallas(src_l[0], pth_l[0], tgt_l[0], seg_l[0],
                             valid_l[0], ws, wp, wt, av, m_l[0], z_l[0],
                             gc_l[0], g_l[0], per_shard, interpret,
                             precision)
        return tuple(o[None] for o in outs)

    if mesh is not None and mesh.size > 1:
        # check_vma=False: same reasoning as the forward kernel route
        outs = shard_map(
            one_shard, mesh=mesh,
            in_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                      P(DATA_AXIS, None, None), P(DATA_AXIS, None),
                      P(DATA_AXIS, None), P(DATA_AXIS, None),
                      P(DATA_AXIS, None), P(DATA_AXIS, None),
                      P(DATA_AXIS, None, None), P(None, None),
                      P(None, None), P(None, None), P(None, None)),
            out_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                       P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                       P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                       P(DATA_AXIS, None, None)),
            check_vma=False)(src_e, pth_e, tgt_e, seg, slot_valid,
                             m, z, gc, g2, w_src, w_path, w_tgt, attn_vec)
        de_src, de_pth, de_tgt, dw_src, dw_pth, dw_tgt, d_attn = outs
        return (de_src, de_pth, de_tgt, dw_src.sum(axis=0),
                dw_pth.sum(axis=0), dw_tgt.sum(axis=0),
                d_attn.sum(axis=0))
    flat = shards * cap
    n_seg = shards * per_shard
    offsets = (jnp.arange(shards, dtype=jnp.int32) * per_shard)[:, None]
    seg_flat = (seg + offsets).reshape(flat)
    outs = _grads_pallas(
        src_e.reshape(flat, -1), pth_e.reshape(flat, -1),
        tgt_e.reshape(flat, -1), seg_flat, slot_valid.reshape(flat),
        w_src, w_path, w_tgt, attn_vec, m.reshape(n_seg),
        z.reshape(n_seg), gc.reshape(n_seg), g2.reshape(n_seg, -1),
        n_seg, interpret, precision)
    de_src, de_pth, de_tgt, dw_src, dw_pth, dw_tgt, d_attn = outs
    return (de_src.reshape(shards, cap, -1),
            de_pth.reshape(shards, cap, -1),
            de_tgt.reshape(shards, cap, -1),
            dw_src, dw_pth, dw_tgt, d_attn)


def _grads_jnp(src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path, w_tgt,
               attn_vec, m, z, gc, g2, precision):
    """jnp twin of the backward kernel — the CPU/fallback recompute
    backward (the residual win applies there too: under the custom VJP
    these per-slot tensors are transients of THIS function, not saved
    forward state). ``g2`` (D, Bs, Dc) f32 per-example cotangents,
    ``gc`` (D, Bs) f32 = sum(g2 * code2). Returns the same tuple as
    _grads_kernel_path."""
    x = jnp.tanh(jnp.matmul(src_e, w_src, precision=precision)
                 + jnp.matmul(pth_e, w_path, precision=precision)
                 + jnp.matmul(tgt_e, w_tgt, precision=precision))
    scores = jnp.matmul(x, attn_vec,
                        precision=precision)[..., 0].astype(jnp.float32)
    m_slot = jnp.take_along_axis(m, seg, axis=1)
    z_slot = jnp.take_along_axis(z, seg, axis=1)
    p = jnp.where(slot_valid, jnp.exp(scores - m_slot), 0.0)
    w = p / jnp.where(z_slot > 0.0, z_slot, 1.0)             # (D, cap)
    g_slot = jnp.take_along_axis(g2, seg[..., None], axis=1)  # (D,cap,Dc)
    gc_slot = jnp.take_along_axis(gc, seg, axis=1)            # (D, cap)
    xf = x.astype(jnp.float32)
    gdot = jnp.sum(xf * g_slot, axis=-1)                      # (D, cap)
    ds = w * (gdot - gc_slot)                                 # (D, cap)
    dx = (w[..., None] * g_slot
          + ds[..., None] * attn_vec[:, 0].astype(jnp.float32))
    du = (1.0 - xf * xf) * dx                                 # (D,cap,Dc)
    d_attn = jnp.einsum('sc,scd->d', ds, xf,
                        precision=precision)[:, None]         # (Dc, 1)
    f32 = jnp.float32
    dw_src = jnp.einsum('sci,scj->ij', src_e.astype(f32), du,
                        precision=precision)
    dw_pth = jnp.einsum('sci,scj->ij', pth_e.astype(f32), du,
                        precision=precision)
    dw_tgt = jnp.einsum('sci,scj->ij', tgt_e.astype(f32), du,
                        precision=precision)
    de_src = jnp.matmul(du, w_src.astype(f32).T, precision=precision)
    de_pth = jnp.matmul(du, w_path.astype(f32).T, precision=precision)
    de_tgt = jnp.matmul(du, w_tgt.astype(f32).T, precision=precision)
    return de_src, de_pth, de_tgt, dw_src, dw_pth, dw_tgt, d_attn


# ------------------------------------------------- custom-VJP train path
def ragged_encode_code(token_embedding: jax.Array,
                       path_embedding: jax.Array, transform: jax.Array,
                       attention: jax.Array, ctx: jax.Array,
                       count: jax.Array, *, token_pad: int, path_pad: int,
                       dtype: jnp.dtype = jnp.float32,
                       dropout_rng: Optional[jax.Array] = None,
                       dropout_keep_rate: float = 1.0,
                       dropout_prng_impl: str = 'threefry2x32',
                       embed_grad_impl: str = 'dense',
                       use_kernel: Optional[bool] = None,
                       interpret: Optional[bool] = None,
                       mesh=None, custom_vjp: bool = True) -> jax.Array:
    """The TRAIN-path encode: packed wire arrays -> code vectors
    ``(B, D) fp32`` under a ``jax.custom_vjp`` whose backward RECOMPUTES
    the per-slot state instead of storing it (module docstring). Only
    the four encoder params are differentiable; ``ctx``/``count``/the
    PRNG key get ``None`` cotangents (the embed_grad.take_rows
    precedent).

    ``use_kernel`` routes BOTH passes: None engages the Pallas pair iff
    a real TPU backend is active (callers gate train-side engagement via
    ``Config.RAGGED_TRAIN_KERNEL`` pending the >=2% flip verdict), False
    pins the jnp twin pair, True forces the kernels (tests:
    ``interpret=True``). ``custom_vjp=False`` is the autodiff reference
    — the twin differentiated by jax, storing its residuals — kept for
    the parity/residual tests."""
    apply_dropout = dropout_rng is not None and dropout_keep_rate < 1.0
    if use_kernel is None:
        use_kernel = PALLAS_AVAILABLE and tpu_backend_active()
    if interpret is None:
        interpret = not tpu_backend_active()
    if not custom_vjp:
        if use_kernel:
            raise ValueError(
                'custom_vjp=False differentiates the jnp twin via '
                'autodiff; the Pallas kernels have no autodiff rule '
                '(pass use_kernel=False)')
        # max_contexts only shapes the attention output, discarded here
        return ragged_encode(
            token_embedding, path_embedding, transform, attention, ctx,
            count, max_contexts=1, token_pad=token_pad, path_pad=path_pad,
            dtype=dtype, dropout_rng=dropout_rng,
            dropout_keep_rate=dropout_keep_rate,
            dropout_prng_impl=dropout_prng_impl,
            embed_grad_impl=embed_grad_impl, use_kernel=False,
            interpret=interpret, mesh=mesh)[0]

    precision = _precision(dtype)

    def _fwd_compute(tok_t, path_t, trans, attn, ctx_, count_, rng_):
        count2, seg, _pos, slot_valid, src, pth, tgt = _segment_inputs(
            ctx_, count_, token_pad, path_pad)
        shards, cap = seg.shape
        per_shard = count2.shape[1]
        token_dim = tok_t.shape[1]
        path_dim = path_t.shape[1]
        # plain takes: the custom VJP below owns the whole backward, so
        # take_rows' selectable-gradient wrapper would be dead weight
        src_e = jnp.take(tok_t, src, axis=0).astype(dtype)
        pth_e = jnp.take(path_t, pth, axis=0).astype(dtype)
        tgt_e = jnp.take(tok_t, tgt, axis=0).astype(dtype)
        if apply_dropout:
            keep_src, keep_pth, keep_tgt = _dropout_parts(
                rng_, dropout_keep_rate, dropout_prng_impl, shards, cap,
                token_dim, path_dim)
            src_e = _apply_keep(src_e, keep_src, dropout_keep_rate)
            pth_e = _apply_keep(pth_e, keep_pth, dropout_keep_rate)
            tgt_e = _apply_keep(tgt_e, keep_tgt, dropout_keep_rate)
        w_src, w_path, w_tgt, attn_vec = _split_weights(
            trans, attn, token_dim, path_dim, dtype)
        _pad_ctx, x_pad = _pad_forward(tok_t, path_t, trans, token_pad,
                                       path_pad, dtype, precision)
        if use_kernel:
            _scores, m, z, acc = _stats_kernel_path(
                src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                w_tgt, attn_vec, per_shard, mesh, interpret, precision)
        else:
            _scores, m, z, acc = _stats_jnp(
                src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                w_tgt, attn_vec, per_shard, precision)
        code = _code_from_stats(z, acc, count2, x_pad)
        return code.reshape(count_.shape[0], -1), m, z

    def _bwd_compute(tok_t, path_t, trans, attn, ctx_, count_, rng_,
                     m, z, code, g):
        count2, seg, _pos, slot_valid, src, pth, tgt = _segment_inputs(
            ctx_, count_, token_pad, path_pad)
        shards, cap = seg.shape
        per_shard = count2.shape[1]
        token_dim = tok_t.shape[1]
        path_dim = path_t.shape[1]
        # recompute: re-gather the embeddings and re-draw the SAME keep
        # mask from the threaded key — nothing per-slot was saved
        src_e = jnp.take(tok_t, src, axis=0).astype(dtype)
        pth_e = jnp.take(path_t, pth, axis=0).astype(dtype)
        tgt_e = jnp.take(tok_t, tgt, axis=0).astype(dtype)
        keep_parts = None
        if apply_dropout:
            keep_parts = _dropout_parts(
                rng_, dropout_keep_rate, dropout_prng_impl, shards, cap,
                token_dim, path_dim)
            src_e = _apply_keep(src_e, keep_parts[0], dropout_keep_rate)
            pth_e = _apply_keep(pth_e, keep_parts[1], dropout_keep_rate)
            tgt_e = _apply_keep(tgt_e, keep_parts[2], dropout_keep_rate)
        w_src, w_path, w_tgt, attn_vec = _split_weights(
            trans, attn, token_dim, path_dim, dtype)
        g32 = g.astype(jnp.float32)
        g2 = g32.reshape(shards, per_shard, -1)
        code2 = code.reshape(shards, per_shard, -1)
        gc = jnp.sum(g2 * code2, axis=-1)                    # (D, Bs)
        if use_kernel:
            (de_src, de_pth, de_tgt, dw_src, dw_pth, dw_tgt,
             d_attn) = _grads_kernel_path(
                src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                w_tgt, attn_vec, m, z, gc, g2, per_shard, mesh,
                interpret, precision)
        else:
            (de_src, de_pth, de_tgt, dw_src, dw_pth, dw_tgt,
             d_attn) = _grads_jnp(
                src_e, pth_e, tgt_e, seg, slot_valid, w_src, w_path,
                w_tgt, attn_vec, m, z, gc, g2, precision)
        if apply_dropout:
            # inverted-dropout backward: same mask, same 1/keep scale
            de_src = _apply_keep(de_src, keep_parts[0], dropout_keep_rate)
            de_pth = _apply_keep(de_pth, keep_parts[1], dropout_keep_rate)
            de_tgt = _apply_keep(de_tgt, keep_parts[2], dropout_keep_rate)
        # count == 0 rows took code = x_pad = tanh(pad_ctx @ W): route
        # their cotangent through that expression. Zero in training
        # (weight-0 rows get zero loss cotangent) but exact for any
        # caller, matching the autodiff twin.
        nonempty = count2 > 0
        g_empty = jnp.where(nonempty[..., None], 0.0,
                            g2).sum(axis=(0, 1))             # (Dc,)
        pad_ctx, x_pad = _pad_forward(tok_t, path_t, trans, token_pad,
                                      path_pad, dtype, precision)
        x_pad32 = x_pad.astype(jnp.float32)
        du_pad = (1.0 - x_pad32 * x_pad32) * g_empty         # (Dc,)
        dw_pad = (pad_ctx.astype(jnp.float32)[:, None]
                  * du_pad[None, :])                         # (3d, Dc)
        de_pad = jnp.matmul(trans.astype(jnp.float32), du_pad,
                            precision=precision)             # (3d,)
        d_trans = (jnp.concatenate([dw_src, dw_pth, dw_tgt], axis=0)
                   + dw_pad).astype(trans.dtype)
        # table grads as segment scatter-adds over the packed index
        # stream — THE reshaped-scatter substrate (ops/embed_grad.py),
        # so EMBED_GRAD_IMPL composes exactly as on the dense path
        from code2vec_tpu.ops.embed_grad import table_grad
        tok_idx = jnp.concatenate([src.reshape(-1), tgt.reshape(-1)])
        tok_cot = jnp.concatenate([de_src.reshape(-1, token_dim),
                                   de_tgt.reshape(-1, token_dim)])
        d_tok = table_grad(tok_cot, tok_idx, tok_t.shape[0], tok_t.dtype,
                           embed_grad_impl)
        d_tok = d_tok.at[token_pad].add(
            (de_pad[:token_dim]
             + de_pad[token_dim + path_dim:]).astype(tok_t.dtype))
        d_path = table_grad(de_pth.reshape(-1, path_dim), pth.reshape(-1),
                            path_t.shape[0], path_t.dtype,
                            embed_grad_impl)
        d_path = d_path.at[path_pad].add(
            de_pad[token_dim:token_dim + path_dim].astype(path_t.dtype))
        return d_tok, d_path, d_trans, d_attn.astype(attn.dtype)

    @jax.custom_vjp
    def encode_code(tok_t, path_t, trans, attn, ctx_, count_, rng_):
        return _fwd_compute(tok_t, path_t, trans, attn, ctx_, count_,
                            rng_)[0]

    def fwd(tok_t, path_t, trans, attn, ctx_, count_, rng_):
        code, m, z = _fwd_compute(tok_t, path_t, trans, attn, ctx_,
                                  count_, rng_)
        # residuals: the inputs (live anyway) + per-example (m, z) +
        # the (B, D) code — NO per-slot tensor
        return code, (tok_t, path_t, trans, attn, ctx_, count_, rng_,
                      m, z, code)

    def bwd(res, g):
        tok_t, path_t, trans, attn, ctx_, count_, rng_, m, z, code = res
        grads = _bwd_compute(tok_t, path_t, trans, attn, ctx_, count_,
                             rng_, m, z, code, g)
        return grads + (None, None, None)

    encode_code.defvjp(fwd, bwd)
    rng_arg = (dropout_rng if apply_dropout
               else jnp.zeros((0,), jnp.uint32))
    return encode_code(token_embedding, path_embedding, transform,
                       attention, ctx, count, rng_arg)
