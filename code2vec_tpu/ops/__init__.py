from code2vec_tpu.ops.topk import sharded_top_k

__all__ = ['sharded_top_k']
