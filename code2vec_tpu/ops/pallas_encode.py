"""Experimental Pallas TPU kernel: fused context transform.

Computes, for N = batch·max_contexts context rows at once,

    x      = tanh(src_e @ W_src + path_e @ W_path + tgt_e @ W_tgt)   (N, D)
    scores = x @ attention                                            (N,)

in one pass over row tiles: the three embedding slices multiply against the
row-split TRANSFORM (reference tensorflow_model.py:249-252 concatenates
first — materializing an (N, 3d) intermediate in HBM), the add/tanh/score
matvec all stay in VMEM, and the transform weights are resident in VMEM for
the whole grid.

OFF by default (``Config.USE_PALLAS_FUSED_ENCODE``; the on-chip A/B
measured it 0.99x vs XLA at the java14m bag size — PERF.md "Pallas
fused-encode kernel"). This kernel consumes DENSE ``(N, d)`` rows, i.e. it
runs after the packed wire has already been scattered back to plane
layout, and it stops at the attention scores — the softmax and weighted
sum stay in XLA. Its successor ``ops/pallas_ragged.py``
(``Config.USE_PALLAS_RAGGED_FUSION``) subsumes both limitations for
packed-wire batches: it walks the packed segments directly (no dense
materialization at all) and carries the fusion through the per-example
attention softmax + reduction in the same pass. This module remains the
plane-wire fallback and the minimal staging ground for row-tile encode
experiments. Correctness is tested in interpreter mode on CPU; numerics
match the jnp path to fp32 rounding.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# shared soft import + TPU predicate (ops/_pallas_common.py); the names
# are re-exported here because model code and the benches historically
# import them from this module
from code2vec_tpu.ops._pallas_common import (PALLAS_AVAILABLE,  # noqa: F401
                                             tpu_backend_active)

if PALLAS_AVAILABLE:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

ROW_TILE = 512  # context rows per grid step; N is padded to a multiple


def _kernel(src_ref, path_ref, tgt_ref, w_src_ref, w_path_ref, w_tgt_ref,
            attn_ref, x_ref, scores_ref):
    x = jnp.dot(src_ref[:], w_src_ref[:],
                preferred_element_type=jnp.float32)
    x += jnp.dot(path_ref[:], w_path_ref[:],
                 preferred_element_type=jnp.float32)
    x += jnp.dot(tgt_ref[:], w_tgt_ref[:],
                 preferred_element_type=jnp.float32)
    x = jnp.tanh(x)
    x_ref[:] = x
    scores_ref[:] = jnp.dot(x, attn_ref[:],
                            preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=('interpret',))
def fused_context_transform(src_e: jax.Array, path_e: jax.Array,
                            tgt_e: jax.Array, transform: jax.Array,
                            attention: jax.Array,
                            interpret: bool = None
                            ) -> Tuple[jax.Array, jax.Array]:
    """(N, d)-shaped gathered embeddings → (x (N, D), scores (N, 1)).

    ``transform`` is the full (2·d_tok + d_path, D) TRANSFORM matrix; it is
    row-split here to skip the concat. ``attention`` is (D, 1).
    ``interpret`` defaults to True off-TPU so the kernel runs (slowly but
    correctly) everywhere.
    """
    if interpret is None:
        interpret = not tpu_backend_active()
    n, token_dim = src_e.shape
    path_dim = path_e.shape[1]
    code_dim = transform.shape[1]
    w_src = transform[:token_dim]
    w_path = transform[token_dim:token_dim + path_dim]
    w_tgt = transform[token_dim + path_dim:]

    padded_n = -(-n // ROW_TILE) * ROW_TILE
    pad = padded_n - n
    if pad:
        src_e = jnp.pad(src_e, ((0, pad), (0, 0)))
        path_e = jnp.pad(path_e, ((0, pad), (0, 0)))
        tgt_e = jnp.pad(tgt_e, ((0, pad), (0, 0)))

    grid = (padded_n // ROW_TILE,)
    row_block = lambda dim: pl.BlockSpec((ROW_TILE, dim),
                                         lambda i: (i, 0))
    full_block = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0))
    x, scores = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            row_block(token_dim), row_block(path_dim), row_block(token_dim),
            full_block(w_src.shape), full_block(w_path.shape),
            full_block(w_tgt.shape), full_block(attention.shape),
        ],
        out_specs=[row_block(code_dim), row_block(1)],
        out_shape=[
            jax.ShapeDtypeStruct((padded_n, code_dim), jnp.float32),
            jax.ShapeDtypeStruct((padded_n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(src_e, path_e, tgt_e, w_src, w_path, w_tgt, attention)
    return x[:n], scores[:n]
