"""Experimental Pallas TPU kernel: fused softmax cross-entropy over the
target vocabulary ("flash CE").

The training loss needs only ``logsumexp(logits)`` and ``logits[label]``
per example (models/functional.py::weighted_ce_sums), yet the XLA path
materializes the full (B, V) logits matrix in HBM to get them — at the
java14m configuration (B=1024, V=261K) that is ~1.07 GB written + read in
the forward and another ~1.07 GB of d(logits) written + read twice in the
backward, ~4.3 GB of the step's 20.6 GB HBM traffic (PERF.md). The
reference pays the same cost on GPU via
``sparse_softmax_cross_entropy_with_logits`` over materialized logits
(reference tensorflow_model.py:226-230).

This kernel streams the target-embedding table through VMEM in vocab
blocks instead, the way flash attention streams keys:

  forward:  online (max, sumexp) accumulation per block -> lse, plus the
            label's logit picked with a block-local one-hot dot; logits
            never leave VMEM.
  backward: recompute each logits block from (code, W_block, lse) and
            contract it immediately: dW_j = dlogits_j^T @ code written
            per block, dcode accumulated in VMEM scratch. d(logits) never
            exists in HBM either.

Multi-device meshes route through :func:`sharded_fused_weighted_ce_sums`,
which shard_maps the kernel: the target table stays row-sharded over the
``model`` axis (each shard streams only its V/m rows), the batch stays
sharded over ``data``, and the per-shard online-softmax stats are merged
with pmax/psum over ICI — the same candidates-only traffic philosophy as
ops/topk.py::sharded_top_k. GSPMD alone cannot do this: a pallas_call is
opaque to the partitioner, so under plain jit it would be replicated
(full batch + full table on every device), negating the sharding.

OFF by default (``Config.USE_PALLAS_FUSED_CE``) until the on-chip A/B
(benchmarks/bench_fused_ce.py) records a win; correctness is tested in
interpreter mode on CPU against the jnp path (tests/test_pallas_ce.py),
including gradients and the sharded variant on a (4, 2) mesh.
Eval/predict keep the materialized-logits path — they need the full
matrix for top-k anyway.
"""
from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from code2vec_tpu.ops._pallas_common import (PALLAS_AVAILABLE,
                                             tpu_backend_active)

if PALLAS_AVAILABLE:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.ops._shard_map import shard_map
from code2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# vocab columns per grid step. VMEM at java14m shapes (B=1024, D=384,
# tile 1024): fwd ~8 MB, bwd ~11 MB incl. the f32 dlogits block, double-
# buffered weight blocks and the dcode accumulator — comfortably under the
# ~16 MB/core budget; 2048 would put the backward at ~18 MB.
# PALLAS_CE_VOCAB_TILE overrides it (VERDICT r3 #4 contingency: if Mosaic
# compile stalls at java14m shapes inside a capture window, the bench
# harness retries with smaller tiles unattended).
_DEFAULT_VOCAB_TILE = 1024


def _parse_vocab_tile(raw: str) -> int:
    """Validate the PALLAS_CE_VOCAB_TILE override instead of letting a bad
    value crash every import (including CPU-only paths) or silently pick a
    tile the kernel can't run: must be a positive multiple of 128 (the TPU
    lane width); above 1024 the backward pass blows the ~16 MB VMEM budget
    (see above), so warn and proceed — Mosaic gives the real verdict."""
    import warnings
    try:
        tile = int(raw)
    except (TypeError, ValueError):
        warnings.warn(
            'PALLAS_CE_VOCAB_TILE=%r is not an integer; using the default '
            '%d' % (raw, _DEFAULT_VOCAB_TILE))
        return _DEFAULT_VOCAB_TILE
    if tile <= 0 or tile % 128:
        warnings.warn(
            'PALLAS_CE_VOCAB_TILE=%d must be a positive multiple of 128; '
            'using the default %d' % (tile, _DEFAULT_VOCAB_TILE))
        return _DEFAULT_VOCAB_TILE
    if tile > 1024:
        warnings.warn(
            'PALLAS_CE_VOCAB_TILE=%d exceeds 1024: the backward pass '
            'likely exceeds the ~16 MB VMEM budget at java14m shapes'
            % tile)
    return tile


VOCAB_TILE = _parse_vocab_tile(
    os.environ.get('PALLAS_CE_VOCAB_TILE', str(_DEFAULT_VOCAB_TILE)))
_NEG = -1e30        # finite -inf stand-in (denormal-safe, like _MASK_MIN)


def _fwd_kernel(precision, code_ref, w_ref, label_ref, nv_ref,
                lse_ref, picked_ref, m_ref, s_ref, p_ref):
    j = pl.program_id(0)
    block = w_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        s_ref[:] = jnp.zeros_like(s_ref)
        p_ref[:] = jnp.zeros_like(p_ref)

    logits = jnp.dot(code_ref[:], w_ref[:].T, precision=precision,
                     preferred_element_type=jnp.float32)      # (B, VB)
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + j * block
    # num_valid arrives as a (1, 1) block so it can be a traced, shard-
    # local value under shard_map (a static closure value could not be)
    valid = col < nv_ref[:]
    logits = jnp.where(valid, logits, _NEG)

    # label pick: at most one VALID column matches per row across ALL
    # blocks. The valid gate matters under shard_map: a label owned by the
    # NEXT shard can collide with this shard's tile-pad window (columns
    # [vshard, padded_vshard)) — ungated, that match would add the _NEG
    # sentinel into the psum-merged pick and explode the loss.
    onehot = jnp.where((col == label_ref[:]) & valid, 1.0, 0.0)
    p_ref[:] += jnp.sum(logits * onehot, axis=1, keepdims=True)

    m_old = m_ref[:]
    m_new = jnp.maximum(m_old, jnp.max(logits, axis=1, keepdims=True))
    s_ref[:] = (s_ref[:] * jnp.exp(m_old - m_new)
                + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new

    @pl.when(j == pl.num_programs(0) - 1)
    def _finish():
        lse_ref[:] = m_ref[:] + jnp.log(s_ref[:])
        picked_ref[:] = p_ref[:]


def _bwd_kernel(precision, code_ref, w_ref, label_ref, nv_ref, lse_ref,
                dlse_ref, dpicked_ref, dw_ref, dcode_ref, acc_ref):
    j = pl.program_id(0)
    block = w_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    logits = jnp.dot(code_ref[:], w_ref[:].T, precision=precision,
                     preferred_element_type=jnp.float32)      # (B, VB)
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + j * block
    valid = col < nv_ref[:]
    softmax = jnp.where(valid, jnp.exp(logits - lse_ref[:]), 0.0)
    # the valid mask keeps the vjp the true linearization even for a
    # label in the masked range: the forward picks the _NEG constant
    # there, which has zero dependence on w and code
    onehot = jnp.where((col == label_ref[:]) & valid, 1.0, 0.0)
    dlogits = dlse_ref[:] * softmax + dpicked_ref[:] * onehot  # (B, VB) f32

    compute_dtype = code_ref.dtype
    dw_ref[:] = jnp.dot(dlogits.astype(compute_dtype).T, code_ref[:],
                        precision=precision,
                        preferred_element_type=jnp.float32)    # (VB, D)
    acc_ref[:] += jnp.dot(dlogits.astype(compute_dtype), w_ref[:],
                          precision=precision,
                          preferred_element_type=jnp.float32)  # (B, D)

    @pl.when(j == pl.num_programs(0) - 1)
    def _finish():
        dcode_ref[:] = acc_ref[:]


def _pad_vocab(w: jax.Array) -> jax.Array:
    v = w.shape[0]
    padded = -(-v // VOCAB_TILE) * VOCAB_TILE
    if padded != v:
        w = jnp.pad(w, ((0, padded - v), (0, 0)))
    return w


def _precision(dtype) -> jax.lax.Precision:
    """Mirror compute_logits: fp32 asks for true-fp32 MXU passes (TPU f32
    matmuls otherwise lower to bf16 passes), bf16 uses the fast path."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _nv_block(num_valid) -> jax.Array:
    """num_valid as the (1, 1) int32 block the kernels read. Accepts a
    static int or a traced scalar (the shard-local clip under shard_map)."""
    return jnp.full((1, 1), num_valid, jnp.int32)


def _forward(code, w, label, num_valid, interpret):
    batch, dim = code.shape
    w = _pad_vocab(w)
    grid = (w.shape[0] // VOCAB_TILE,)
    label2d = label.astype(jnp.int32).reshape(batch, 1)
    kernel = functools.partial(_fwd_kernel, _precision(code.dtype))
    lse, picked = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((batch, dim), lambda j: (0, 0)),        # code
            pl.BlockSpec((VOCAB_TILE, dim), lambda j: (j, 0)),   # w block
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),          # label
            pl.BlockSpec((1, 1), lambda j: (0, 0)),              # num_valid
        ],
        out_specs=[
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, 1), jnp.float32),
            jax.ShapeDtypeStruct((batch, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((batch, 1), jnp.float32),   # running max
            pltpu.VMEM((batch, 1), jnp.float32),   # running sumexp
            pltpu.VMEM((batch, 1), jnp.float32),   # picked accumulator
        ],
        interpret=interpret,
    )(code, w, label2d, _nv_block(num_valid))
    return lse[:, 0], picked[:, 0]


def _backward(code, w, label, lse, dlse, dpicked, num_valid, interpret
              ) -> Tuple[jax.Array, jax.Array]:
    """(dw (V, D) f32, dcode (B, D) f32) from the saved lse — logits are
    recomputed blockwise, d(logits) never exists in HBM."""
    batch, dim = code.shape
    v = w.shape[0]
    w_padded = _pad_vocab(w)
    grid = (w_padded.shape[0] // VOCAB_TILE,)
    label2d = label.astype(jnp.int32).reshape(batch, 1)
    kernel = functools.partial(_bwd_kernel, _precision(code.dtype))
    dw, dcode = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((batch, dim), lambda j: (0, 0)),        # code
            pl.BlockSpec((VOCAB_TILE, dim), lambda j: (j, 0)),   # w block
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),          # label
            pl.BlockSpec((1, 1), lambda j: (0, 0)),              # num_valid
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),          # lse
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),          # dlse
            pl.BlockSpec((batch, 1), lambda j: (0, 0)),          # dpicked
        ],
        out_specs=[
            pl.BlockSpec((VOCAB_TILE, dim), lambda j: (j, 0)),   # dw block
            pl.BlockSpec((batch, dim), lambda j: (0, 0)),        # dcode
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w_padded.shape[0], dim), jnp.float32),
            jax.ShapeDtypeStruct((batch, dim), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((batch, dim), jnp.float32),  # dcode accumulator
        ],
        interpret=interpret,
    )(code, w_padded, label2d, _nv_block(num_valid),
      lse.reshape(batch, 1),
      dlse.reshape(batch, 1).astype(jnp.float32),
      dpicked.reshape(batch, 1).astype(jnp.float32))
    return dw[:v], dcode


# ------------------------------------------------------- single device
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_lse_and_pick(code: jax.Array, w: jax.Array, label: jax.Array,
                       num_valid: int, interpret: bool
                       ) -> Tuple[jax.Array, jax.Array]:
    """(lse (B,), picked (B,)) of ``code @ w.T`` without materializing the
    (B, V) logits in HBM. ``num_valid`` masks padded vocab columns;
    ``label`` out-of-range rows pick 0 (they must carry weight 0, exactly
    like the XLA path's padded rows)."""
    lse, picked = _forward(code, w, label, num_valid, interpret)
    return lse, picked


def _vjp_fwd(code, w, label, num_valid, interpret):
    lse, picked = _forward(code, w, label, num_valid, interpret)
    return (lse, picked), (code, w, label, lse)


def _vjp_bwd(num_valid, interpret, residuals, cotangents):
    code, w, label, lse = residuals
    dlse, dpicked = cotangents
    dw, dcode = _backward(code, w, label, lse, dlse, dpicked,
                          num_valid, interpret)
    return (dcode.astype(code.dtype), dw.astype(w.dtype), None)


fused_lse_and_pick.defvjp(_vjp_fwd, _vjp_bwd)


def fused_weighted_ce_sums(params_target: jax.Array, code_vectors: jax.Array,
                           label: jax.Array, weight: jax.Array,
                           num_valid_targets: int,
                           dtype: jnp.dtype = jnp.float32,
                           interpret: bool = None
                           ) -> Tuple[jax.Array, jax.Array]:
    """Drop-in for compute_logits + weighted_ce_sums in the TRAIN path:
    (weighted CE sum, weight sum) with no (B, V) HBM intermediate.

    ``dtype`` is the MXU compute dtype, mirroring compute_logits: the
    matmuls run in ``dtype`` with fp32 accumulation, reductions stay fp32.
    """
    if interpret is None:
        interpret = not tpu_backend_active()
    lse, picked = fused_lse_and_pick(
        code_vectors.astype(dtype), params_target.astype(dtype),
        label, num_valid_targets, interpret)
    ce = lse - picked
    return (ce * weight).sum(), weight.sum()


# ------------------------------------------------ sharded (multi-device)
def _shard_offset(vocab_per_shard: int) -> jax.Array:
    return (jax.lax.axis_index(MODEL_AXIS) * vocab_per_shard).astype(
        jnp.int32)


def _sharded_forward(code, w, label, num_valid, mesh, interpret):
    vshard = w.shape[0] // mesh.shape[MODEL_AXIS]

    def local(code_blk, w_blk, label_blk):
        offset = _shard_offset(vshard)
        # labels owned by another shard fall out of [0, vshard) and match
        # no column; a shard whose rows are ALL allocation padding gets
        # local_valid == 0, every column masked to _NEG, and its
        # exp(lse - m) underflows to exactly 0 in the merge below
        lse_l, picked_l = _forward(
            code_blk, w_blk, label_blk.astype(jnp.int32) - offset,
            jnp.clip(num_valid - offset, 0, vshard), interpret)
        m = jax.lax.pmax(lse_l, MODEL_AXIS)
        lse = m + jnp.log(jax.lax.psum(jnp.exp(lse_l - m), MODEL_AXIS))
        picked = jax.lax.psum(picked_l, MODEL_AXIS)
        return lse, picked

    # check_vma=False: outputs ARE replicated along 'model' after the
    # psum/pmax merge, but the static checker can't prove it (same as
    # ops/topk.py::sharded_top_k)
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False)(code, w, label)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def sharded_fused_lse_and_pick(code: jax.Array, w: jax.Array,
                               label: jax.Array, num_valid: int, mesh: Mesh,
                               interpret: bool
                               ) -> Tuple[jax.Array, jax.Array]:
    """fused_lse_and_pick over a (data, model) mesh: ``w`` row-sharded over
    ``model``, ``code``/``label`` sharded over ``data``. Per-shard online
    stats merge over ICI; cross-shard traffic is O(B) scalars per merge,
    never logits. The vjp is explicit (a second shard_map) rather than
    relying on collective transposition through the forward."""
    return _sharded_forward(code, w, label, num_valid, mesh, interpret)


def _sharded_vjp_fwd(code, w, label, num_valid, mesh, interpret):
    lse, picked = _sharded_forward(code, w, label, num_valid, mesh,
                                   interpret)
    return (lse, picked), (code, w, label, lse)


def _sharded_vjp_bwd(num_valid, mesh, interpret, residuals, cotangents):
    code, w, label, lse = residuals
    dlse, dpicked = cotangents
    vshard = w.shape[0] // mesh.shape[MODEL_AXIS]

    def local(code_blk, w_blk, label_blk, lse_blk, dlse_blk, dpicked_blk):
        offset = _shard_offset(vshard)
        # the GLOBAL lse is the residual, so each shard's recomputed
        # softmax block is already globally normalized; dw stays local to
        # the shard's rows, dcode sums contributions from every shard
        dw_l, dcode_p = _backward(
            code_blk, w_blk, label_blk.astype(jnp.int32) - offset, lse_blk,
            dlse_blk, dpicked_blk,
            jnp.clip(num_valid - offset, 0, vshard), interpret)
        # each partial is complete along its OWN axis only: dcode_p saw
        # just this shard's vocab rows (psum over model), dw_l saw just
        # this shard's batch rows (psum over data — the DP grad reduction
        # GSPMD would otherwise insert outside the shard_map)
        return (jax.lax.psum(dcode_p, MODEL_AXIS),
                jax.lax.psum(dw_l, DATA_AXIS))

    dcode, dw = shard_map(
        local, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None), P(DATA_AXIS),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        check_vma=False)(code, w, label, lse,
                         dlse.astype(jnp.float32),
                         dpicked.astype(jnp.float32))
    return (dcode.astype(code.dtype), dw.astype(w.dtype), None)


sharded_fused_lse_and_pick.defvjp(_sharded_vjp_fwd, _sharded_vjp_bwd)


def sharded_fused_weighted_ce_sums(params_target: jax.Array,
                                   code_vectors: jax.Array,
                                   label: jax.Array, weight: jax.Array,
                                   num_valid_targets: int, mesh: Mesh,
                                   dtype: jnp.dtype = jnp.float32,
                                   interpret: bool = None
                                   ) -> Tuple[jax.Array, jax.Array]:
    """Multi-device drop-in for fused_weighted_ce_sums. Requires the
    padded target vocab divisible by the model axis (the trainer's
    PARAM_ROW_ALIGNMENT check guarantees it); per-shard rows that are not
    a VOCAB_TILE multiple still work via the kernel's own pad, at the cost
    of a per-step copy of the local shard (backends align the allocation
    to avoid this)."""
    if interpret is None:
        interpret = not tpu_backend_active()
    lse, picked = sharded_fused_lse_and_pick(
        code_vectors.astype(dtype), params_target.astype(dtype),
        label, num_valid_targets, mesh, interpret)
    ce = lse - picked
    return (ce * weight).sum(), weight.sum()
