"""Shared plumbing for the Pallas TPU kernel modules.

One definition of the soft Pallas import and the TPU-backend predicate,
used by all three kernels (``ops/pallas_encode.py``, ``ops/pallas_ce.py``,
``ops/pallas_ragged.py``) so the routing discipline cannot drift between
them: the kernels engage only when the DEVICE platform is a real TPU, and
every module keeps importing cleanly on CPU-only installs.
"""
from __future__ import annotations

import jax

try:  # pallas is TPU-oriented; keep the import soft for CPU-only installs
    from jax.experimental import pallas as pl                 # noqa: F401
    from jax.experimental.pallas import tpu as pltpu          # noqa: F401
    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover
    pl = None
    pltpu = None
    PALLAS_AVAILABLE = False


def tpu_backend_active() -> bool:
    """True iff the default backend's devices are real TPUs. Checks the
    DEVICE platform, not ``jax.default_backend()``: behind device-tunnel
    plugins the backend may register under another name (e.g. 'axon')
    while its devices report platform 'tpu' — gating on the backend name
    silently reroutes the kernel to the plain XLA path."""
    try:
        devices = jax.devices()
    except RuntimeError:
        return False
    return bool(devices) and devices[0].platform.lower() == 'tpu'
