"""Shared host-side helpers (string normalization, histograms, word2vec IO).

Pure-Python re-design of the reference ``common.py``: everything here runs on
the host; nothing imports a DL framework (the reference mixed tf helpers into
the same grab-bag, common.py:160-164 — those live in device code here).
"""
from __future__ import annotations

import re
from collections import OrderedDict
from datetime import datetime
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

_NON_ALPHA_RE = re.compile(r'[^a-zA-Z]')
_LEGAL_NAME_RE = re.compile(r'^[a-zA-Z|]+$')


def normalize_word(word: str) -> str:
    """Strip non-alphabetic chars and lowercase; fall back to plain lowercase
    for fully non-alpha words (reference common.py:12-18)."""
    stripped = _NON_ALPHA_RE.sub('', word)
    if not stripped:
        return word.lower()
    return stripped.lower()


def get_subtokens(word: str) -> List[str]:
    """Subtokens are joined by ``|`` by the extractor
    (reference common.py:131-133)."""
    return word.split('|')


def legal_method_name(oov_word: str, name: str) -> bool:
    """A prediction is 'legal' iff it is not OOV and consists only of letters
    and ``|`` separators (reference common.py:122-124)."""
    return name != oov_word and bool(_LEGAL_NAME_RE.match(name))


def filter_impossible_names(oov_word: str, top_words: Iterable[str]) -> List[str]:
    return [word for word in top_words if legal_method_name(oov_word, word)]


def get_first_match_word_from_top_predictions(
        oov_word: str, original_name: str,
        top_predicted_words: Iterable[str]) -> Optional[Tuple[int, str]]:
    """Rank (within the legal predictions) of the first prediction matching
    the normalized original name (reference common.py:180-187)."""
    normalized_original = normalize_word(original_name)
    for idx, predicted in enumerate(filter_impossible_names(oov_word, top_predicted_words)):
        if normalized_original == normalize_word(predicted):
            return idx, predicted
    return None


# ------------------------------------------------------------------ histograms
def truncate_histogram_to_max_size(word_to_count: Dict[str, int],
                                   max_size: int) -> Dict[str, int]:
    """Keep words with count ≥ one plus the count of the ``max_size``-th word
    — the reference's histogram cutoff rule (common.py:47-58)."""
    if len(word_to_count) <= max_size:
        return dict(word_to_count)
    cutoff = sorted(word_to_count.values(), reverse=True)[max_size] + 1
    return {w: c for w, c in word_to_count.items() if c >= cutoff}


def load_histogram(path: str, min_count: int = 0,
                   max_size: Optional[int] = None) -> Dict[str, int]:
    """Load a ``word count`` histogram file into a dict, keeping at most
    ``max_size`` highest-count entries (reference common.py:21-58)."""
    word_to_count: Dict[str, int] = {}
    with open(path, 'r') as file:
        for line in file:
            parts = line.rstrip().split(' ')
            if len(parts) != 2:
                continue
            word, count_str = parts
            count = int(count_str)
            if count < min_count or word in word_to_count:
                continue
            word_to_count[word] = count
    if max_size is not None:
        word_to_count = truncate_histogram_to_max_size(word_to_count, max_size)
    return word_to_count


# ------------------------------------------------------------------- word2vec
def save_word2vec_file(output_file, index_to_word: Dict[int, str],
                       embedding_matrix: np.ndarray) -> None:
    """Textual word2vec format: header line then ``word v0 v1 …`` rows
    (reference common.py:82-91)."""
    assert embedding_matrix.ndim == 2
    vocab_size, dim = embedding_matrix.shape
    output_file.write('%d %d\n' % (vocab_size, dim))
    for word_idx in range(vocab_size):
        assert word_idx in index_to_word
        output_file.write(index_to_word[word_idx] + ' ')
        output_file.write(' '.join(map(str, embedding_matrix[word_idx])) + '\n')


# ------------------------------------------------------------------ small utils
def count_lines_in_file(file_path: str) -> int:
    """Buffered newline count (reference common.py:166-170)."""
    count = 0
    with open(file_path, 'rb') as f:
        while True:
            buf = f.read(1024 * 1024)
            if not buf:
                return count
            count += buf.count(b'\n')


def load_file_lines(path: str) -> List[str]:
    with open(path, 'r') as f:
        return f.read().splitlines()


def split_to_batches(data_lines: List, batch_size: int):
    for start in range(0, len(data_lines), batch_size):
        yield data_lines[start:start + batch_size]


def get_unique_list(items: Iterable) -> list:
    return list(OrderedDict((item, 0) for item in items).keys())


def now_str() -> str:
    return datetime.now().strftime('%Y%m%d-%H%M%S: ')


def java_string_hashcode(s: str) -> int:
    """Clone of Java ``String#hashCode`` used to un-hash paths for display
    (reference extractor.py:40-49)."""
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    if h > 0x7FFFFFFF:
        h -= 0x100000000
    return h


class MethodPredictionResults:
    """Pretty-printable per-method prediction bundle for the serving REPL
    (reference common.py:204-217)."""

    def __init__(self, original_name: str):
        self.original_name = original_name
        self.predictions: List[dict] = []
        self.attention_paths: List[dict] = []

    def append_prediction(self, name: List[str], probability: float) -> None:
        self.predictions.append({'name': name, 'probability': probability})

    def append_attention_path(self, attention_score: float, token1: str,
                              path: str, token2: str) -> None:
        self.attention_paths.append({'score': attention_score, 'path': path,
                                     'token1': token1, 'token2': token2})


def parse_prediction_results(raw_prediction_results, unhash_dict,
                             oov_word: str, topk: int = 5
                             ) -> List[MethodPredictionResults]:
    """Convert raw model predictions into display-ready results: drop OOV,
    split subtokens, un-hash the top-k attended paths
    (reference common.py:135-158)."""
    results = []
    for raw in raw_prediction_results:
        method_result = MethodPredictionResults(raw.original_name)
        for i, predicted in enumerate(raw.topk_predicted_words):
            if predicted == oov_word:
                continue
            method_result.append_prediction(
                get_subtokens(predicted),
                float(raw.topk_predicted_words_scores[i]))
        sorted_contexts = sorted(raw.attention_per_context.items(),
                                 key=lambda kv: kv[1], reverse=True)[:topk]
        for (token1, hashed_path, token2), attention in sorted_contexts:
            if hashed_path in unhash_dict:
                method_result.append_attention_path(
                    float(attention), token1=token1,
                    path=unhash_dict[hashed_path], token2=token2)
        results.append(method_result)
    return results
