"""Evaluation metrics with exact reference semantics.

The device computes top-k indices/scores per batch (a (B, k) int32 transfer —
tiny); the host decodes words and updates streaming accumulators. This mirrors
the reference's split (tensorflow_model.py:156-183 runs top_k in-graph and the
Counter math in Python) while keeping everything string-shaped off the device.

Metric definitions (parity-critical — they define the headline F1):

- **Top-k accuracy** (tensorflow_model.py:499-516 + common.py:180-187):
  an example scores a hit at ranks ≥ r where r is the index of the first
  *legal* prediction whose normalized form equals the normalized original
  name; rank counts only legal predictions.
- **Subtoken precision/recall/F1** (tensorflow_model.py:450-496): per example
  take the FIRST legal prediction of the top-k, split both it and the
  original name on ``|``, and accumulate multiset TP/FP/FN counts.
"""
from __future__ import annotations

from collections import Counter
from typing import Iterable, List, Sequence, Tuple

import numpy as np

from code2vec_tpu import common


class SubtokensEvaluationMetric:
    """Streaming subtoken TP/FP/FN (reference tensorflow_model.py:450-496).

    Deviation from the reference: when none of the top-k predictions is
    legal, the reference crashes with IndexError (:460); here the prediction
    is treated as empty — one false positive plus all-original-subtokens
    false negatives — so early/tiny models evaluate cleanly.
    """

    def __init__(self, oov_word: str):
        self.oov_word = oov_word
        self.nr_true_positives = 0
        self.nr_false_positives = 0
        self.nr_false_negatives = 0
        self.nr_predictions = 0

    def update_batch(self, results: Iterable[Tuple[str, Sequence[str]]]) -> None:
        for original_name, top_words in results:
            legal = common.filter_impossible_names(self.oov_word, top_words)
            prediction = legal[0] if legal else ''
            original_subtokens = Counter(common.get_subtokens(original_name))
            predicted_subtokens = Counter(common.get_subtokens(prediction))
            self.nr_true_positives += sum(
                count for element, count in predicted_subtokens.items()
                if element in original_subtokens)
            self.nr_false_positives += sum(
                count for element, count in predicted_subtokens.items()
                if element not in original_subtokens)
            self.nr_false_negatives += sum(
                count for element, count in original_subtokens.items()
                if element not in predicted_subtokens)
            self.nr_predictions += 1

    def count_vector(self) -> np.ndarray:
        """Raw accumulator counts, for exact cross-process merging
        (multi-host eval sums these and calls ``set_count_vector``)."""
        return np.array([self.nr_true_positives, self.nr_false_positives,
                         self.nr_false_negatives, self.nr_predictions],
                        dtype=np.int64)

    def set_count_vector(self, counts: np.ndarray) -> None:
        (self.nr_true_positives, self.nr_false_positives,
         self.nr_false_negatives, self.nr_predictions) = (
            int(c) for c in counts)

    @property
    def precision(self) -> float:
        denom = self.nr_true_positives + self.nr_false_positives
        return self.nr_true_positives / denom if denom else 0.0

    @property
    def recall(self) -> float:
        denom = self.nr_true_positives + self.nr_false_negatives
        return self.nr_true_positives / denom if denom else 0.0

    @property
    def f1(self) -> float:
        if self.precision + self.recall == 0:
            return 0.0
        return 2 * self.precision * self.recall / (self.precision + self.recall)


class TopKAccuracyEvaluationMetric:
    """Normalized first-match rank accuracy
    (reference tensorflow_model.py:499-516)."""

    def __init__(self, top_k: int, oov_word: str):
        self.top_k = top_k
        self.oov_word = oov_word
        self.nr_correct_predictions = np.zeros(top_k)
        self.nr_predictions = 0

    def update_batch(self, results: Iterable[Tuple[str, Sequence[str]]]) -> None:
        for original_name, top_predicted_words in results:
            self.nr_predictions += 1
            found_match = common.get_first_match_word_from_top_predictions(
                self.oov_word, original_name, top_predicted_words)
            if found_match is not None:
                suggestion_idx, _ = found_match
                self.nr_correct_predictions[suggestion_idx:self.top_k] += 1

    def count_vector(self) -> np.ndarray:
        """Raw accumulator counts, for exact cross-process merging."""
        return np.concatenate([[self.nr_predictions],
                               self.nr_correct_predictions]).astype(np.int64)

    def set_count_vector(self, counts: np.ndarray) -> None:
        self.nr_predictions = int(counts[0])
        self.nr_correct_predictions = counts[1:].astype(np.float64)

    @property
    def topk_correct_predictions(self) -> np.ndarray:
        if self.nr_predictions == 0:
            return np.zeros(self.top_k)
        return self.nr_correct_predictions / self.nr_predictions


def decode_topk_batch(topk_indices: np.ndarray, index_to_word: np.ndarray,
                      label_strings: Sequence[str],
                      weights: np.ndarray) -> List[Tuple[str, List[str]]]:
    """Device (B, k) top-k indices + host label strings →
    [(original_name, [top words...])] for valid rows only."""
    words = index_to_word[topk_indices]          # (B, k) object array
    return [(label_strings[r], list(words[r]))
            for r in range(topk_indices.shape[0]) if weights[r] > 0]
